"""Worker fair scheduling: multilevel-feedback time sharing across queries
(reference test model: TestMultilevelSplitQueue / TestTaskExecutor over
executor/timesharing/MultilevelSplitQueue.java:41,
PrioritizedSplitRunner.java:49 — round-4 verdict item 6)."""

import json
import pickle
import threading
import time

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.execution.fair_scheduler import FairScheduler
from trino_tpu.exec.fte import SpoolingExchange
from trino_tpu.server.cluster import WorkerServer, _http
from trino_tpu.sql import plan as P
from trino_tpu.sql.frontend import compile_sql

CATALOGS = {"tpch": {"connector": "tpch", "sf": 0.05, "split_rows": 1 << 10}}


# ----------------------------------------------------------------- unit level
def test_scheduler_grants_low_level_first():
    s = FairScheduler(slots=1, quantum=10.0)
    s.sched_time["long"] = 30.0  # level 3 (>= 10s served)
    order = []

    s.acquire("long", "t-long")

    def waiter(qk, tok):
        s.acquire(qk, tok)
        order.append(tok)
        s.release(tok)

    a = threading.Thread(target=waiter, args=("long", "t-long2"))
    b = threading.Thread(target=waiter, args=("fresh", "t-fresh"))
    a.start()
    time.sleep(0.1)
    b.start()
    time.sleep(0.1)
    s.release("t-long")  # both waiting: the FRESH query must win despite FIFO
    a.join(timeout=5)
    b.join(timeout=5)
    assert order == ["t-fresh", "t-long2"], order


def test_tick_preempts_for_less_served_query():
    s = FairScheduler(slots=1, quantum=10.0)
    s.sched_time["long"] = 30.0
    s.acquire("long", "t1")
    state = {}

    def short():
        s.acquire("fresh", "t2")
        state["got"] = time.monotonic()
        s.release("t2")

    th = threading.Thread(target=short)
    th.start()
    time.sleep(0.1)
    t0 = time.monotonic()
    s.tick("t1")  # less-served waiter -> must yield and re-acquire
    th.join(timeout=5)
    assert "got" in state and state["got"] >= t0
    assert s.preemptions == 1
    s.release("t1")


def test_tick_round_robins_within_level_after_quantum():
    s = FairScheduler(slots=1, quantum=0.05)
    s.acquire("a", "ta")
    got = []

    def other():
        s.acquire("b", "tb")
        got.append("b")
        s.release("tb")

    th = threading.Thread(target=other)
    th.start()
    time.sleep(0.1)  # same level (both ~0 served) but quantum expired
    s.tick("ta")
    th.join(timeout=5)
    assert got == ["b"]
    s.release("ta")


def test_no_yield_without_waiters():
    s = FairScheduler(slots=2, quantum=0.0)
    s.acquire("a", "t1")
    s.tick("t1")  # nobody waiting: keep the slot even with expired quantum
    assert s.preemptions == 0
    assert "t1" not in s._waiters
    s.release("t1")


# ------------------------------------------------------------- worker level
@pytest.mark.slow
def test_point_query_overtakes_long_scan(tmp_path, monkeypatch):
    """One-slot worker: a long scan-aggregation yields at split boundaries so
    a point query finishes while the long one is still running (the
    reference's short-query-overtakes-ETL property)."""
    monkeypatch.setenv("TRINO_TPU_WORKER_EXEC_SLOTS", "1")
    monkeypatch.setenv("TRINO_TPU_SCHED_QUANTUM", "0.05")
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.05, split_rows=1 << 10))
    s = e.create_session("tpch")
    w = WorkerServer(CATALOGS, str(tmp_path / "spool"))
    url = w.start()
    try:
        long_plan = compile_sql(
            "select l_orderkey, sum(l_quantity) q from lineitem "
            "group by l_orderkey", e, s)
        agg = None

        def find(n):
            nonlocal agg
            if isinstance(n, P.Aggregate) and agg is None:
                agg = n
            for c in n.children:
                find(c)

        find(long_plan)
        assert agg is not None
        splits = list(e.catalogs["tpch"].splits("lineitem"))
        assert len(splits) >= 8, "need enough splits for preemption points"
        short_plan = compile_sql(
            "select c_custkey, c_acctbal from customer "
            "order by c_acctbal desc limit 3", e, s)
        xdir = str(tmp_path / "x")
        _http(f"{url}/v1/fragment",
              pickle.dumps({"fragment_id": "f-long", "plan": agg}))
        _http(f"{url}/v1/fragment",
              pickle.dumps({"fragment_id": "f-short", "plan": short_plan}))
        _http(f"{url}/v1/task",
              pickle.dumps({"task_id": "t-long", "fragment_id": "f-long",
                            "kind": "partial_agg", "exchange_dir": xdir,
                            "splits": tuple(splits)}))
        time.sleep(0.5)  # the long task is mid-flight, holding the only slot
        t0 = time.time()
        _http(f"{url}/v1/task",
              pickle.dumps({"task_id": "t-short", "fragment_id": "f-short",
                            "kind": "fragment", "exchange_dir": xdir}))
        ex = SpoolingExchange(xdir)
        deadline = time.time() + 120
        while time.time() < deadline and not ex.is_committed("t-short"):
            st = json.loads(_http(f"{url}/v1/task/t-short"))
            assert st.get("state") != "failed", st
            time.sleep(0.02)
        short_elapsed = time.time() - t0
        assert ex.is_committed("t-short"), "point query never finished"
        long_running = json.loads(
            _http(f"{url}/v1/task/t-long")).get("state") == "running"
        # the long task yielded: either it is still going, or preemption is
        # recorded in the scheduler stats
        info = json.loads(_http(f"{url}/v1/info"))
        sched = info["scheduler"]
        assert long_running or sched["preemptions"] >= 1, (
            short_elapsed, sched)
        assert len(sched["scheduled_time"]) >= 1  # per-query time is visible
        # drive the long task to completion so the worker shuts down clean
        deadline = time.time() + 300
        while time.time() < deadline and not ex.is_committed("t-long"):
            st = json.loads(_http(f"{url}/v1/task/t-long"))
            assert st.get("state") != "failed", st
            time.sleep(0.1)
        assert ex.is_committed("t-long")
    finally:
        w.stop()


def test_duplicate_task_ids_hold_separate_slots():
    """Speculative duplicates / wedged-task re-dispatch of the same task id
    must count as two slot holders (post-review hardening: token-keyed
    accounting must not alias)."""
    s = FairScheduler(slots=2, quantum=10.0)
    t1 = s.new_token("t7")
    t2 = s.new_token("t7")
    assert t1 != t2
    s.acquire("q", t1)
    s.acquire("q", t2)
    assert len(s._running) == 2
    s.release(t1)
    assert len(s._running) == 1
    s.release(t2)


def test_aging_prevents_starvation():
    """A long query's waiter gains priority as it starves: with fresh
    queries continuously arriving, the aged waiter eventually wins."""
    s = FairScheduler(slots=1, quantum=0.01)
    s.sched_time["etl"] = 100.0  # level 4
    s.acquire("etl", "t-etl-run")
    got = []

    def etl_reacquire():
        s.acquire("etl", "t-etl2")
        got.append("etl")
        s.release("t-etl2")

    th = threading.Thread(target=etl_reacquire)
    th.start()
    time.sleep(0.3)  # waiter ages: 0.3s / (10 * 0.01s) = 3 levels of boost
    # fresh queries keep arriving but the aged ETL waiter must win soon
    deadline = time.time() + 10
    s.release("t-etl-run")
    while not got and time.time() < deadline:
        tok = s.new_token("pt")
        s.acquire("fresh%d" % (time.time_ns() % 97), tok)
        time.sleep(0.02)
        s.release(tok)
    th.join(timeout=10)
    assert got == ["etl"], "aged waiter starved behind fresh queries"


def test_acquire_wakes_on_notify_without_polling():
    """A blocked acquire must wake by condition-variable NOTIFICATION, not by
    a poll interval elapsing: the pre-fix code re-checked every 50ms, adding
    up to a poll interval of latency per grant.  Waits may carry only the
    coarse aging-boundary backstop (>= 10 quanta — liveness against
    aging flipping the grant order with no notify), never a sub-second poll,
    and the grant arrives as soon as release() notifies."""
    s = FairScheduler(slots=1, quantum=10.0)
    waits = []
    orig_wait = s._cv.wait

    def recording_wait(timeout=None):
        waits.append(timeout)
        return orig_wait(timeout)

    s._cv.wait = recording_wait
    tok1, tok2 = s.new_token("a"), s.new_token("b")
    s.acquire("qa", tok1)
    granted = []

    def blocked():
        s.acquire("qb", tok2)
        granted.append(time.monotonic())
        s.release(tok2)

    th = threading.Thread(target=blocked)
    th.start()
    time.sleep(0.1)  # the waiter is parked inside a backstop-only cv.wait
    assert not granted
    released_at = time.monotonic()
    s.release(tok1)
    th.join(timeout=5)
    assert granted, "blocked acquire never woke after release"
    assert waits and all(t is None or t >= 10.0 * s.quantum for t in waits), (
        f"acquire used short (polling) wait timeouts: {waits}")
    # notification latency, not a 50ms poll boundary (generous bound for a
    # loaded 1-core box; the wait-timeout assertion above is the real proof)
    assert granted[0] - released_at < 1.0


def test_sched_time_is_bounded():
    from trino_tpu.execution.fair_scheduler import MAX_TRACKED_QUERIES

    s = FairScheduler(slots=1, quantum=10.0)
    for i in range(MAX_TRACKED_QUERIES + 50):
        tok = s.new_token("t")
        s.acquire(f"q{i}", tok)
        s.release(tok)
    assert len(s.sched_time) <= MAX_TRACKED_QUERIES
    assert len(s.info()["scheduled_time"]) <= 16
