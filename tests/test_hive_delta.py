"""Hive partitioned-directory and Delta Lake connectors (reference test
models: plugin/trino-hive TestHivePartitionedTables-style cases over a
directory layout; plugin/trino-delta-lake TestDeltaLakeBasic over a
hand-authored _delta_log)."""

import datetime
import json
import os

import numpy as np
import pandas as pd
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.delta import DeltaConnector
from trino_tpu.connectors.hive import HiveConnector


def _write_parquet(path, cols: dict):
    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(os.path.dirname(path), exist_ok=True)
    pq.write_table(pa.table(cols), path)


@pytest.fixture()
def hive_wh(tmp_path):
    wh = str(tmp_path / "wh")
    for ds, region, ids in [("2024-01-01", "emea", [1, 2]),
                            ("2024-01-01", "apac", [3]),
                            ("2024-01-02", "emea", [4, 5, 6])]:
        _write_parquet(
            os.path.join(wh, "events", f"ds={ds}", f"region={region}",
                         "part-0.parquet"),
            {"id": [int(i) for i in ids],
             "amount": [float(i) * 1.5 for i in ids]})
    return wh


def test_hive_partition_discovery_and_scan(hive_wh):
    e = Engine()
    e.register_catalog("hive", HiveConnector(hive_wh))
    s = e.create_session("hive")
    r = e.execute_sql(
        "select id, amount, ds, region from events order by id", s).to_pandas()
    assert r["id"].tolist() == [1, 2, 3, 4, 5, 6]
    assert r["region"].tolist() == ["emea", "emea", "apac", "emea", "emea",
                                    "emea"]
    # ds inferred as DATE from the partition path strings; dates decode to
    # datetime64 at the result surface
    import pandas as pd

    d1, d2 = pd.Timestamp("2024-01-01"), pd.Timestamp("2024-01-02")
    assert [pd.Timestamp(v) for v in r["ds"]] == [d1, d1, d1, d2, d2, d2]


def test_hive_partition_pruning_prunes_splits(hive_wh):
    conn = HiveConnector(hive_wh)
    e = Engine()
    e.register_catalog("hive", conn)
    s = e.create_session("hive")
    # string partition equality: domains live in dictionary-id space
    r = e.execute_sql(
        "select count(*) c from events where region = 'apac'", s).to_pandas()
    assert int(r.iloc[0, 0]) == 1
    # the split_range surface prunes exactly: only one split overlaps apac's id
    apac_id = next(i for i, v in enumerate(
        conn.dictionaries("events")["region"].values) if v == "apac")
    ranges = [conn.split_range(sp, "region") for sp in conn.splits("events")]
    assert (apac_id, apac_id) in ranges
    assert sum(1 for rg in ranges if rg == (apac_id, apac_id)) == 1


def test_hive_group_by_partition_column(hive_wh):
    e = Engine()
    e.register_catalog("hive", HiveConnector(hive_wh))
    s = e.create_session("hive")
    r = e.execute_sql(
        "select region, count(*) c, sum(id) si from events "
        "group by region order by region", s).to_pandas()
    assert r.values.tolist() == [["apac", 1, 3], ["emea", 5, 18]]


def test_hive_partitioned_write_roundtrip(tmp_path):
    from trino_tpu.page import Field, Schema
    from trino_tpu.types import BIGINT, VarcharType

    wh = str(tmp_path / "whw")
    conn = HiveConnector(wh)
    schema = Schema((Field("id", BIGINT), Field("name", VarcharType.of(None)),
                     Field("ds", VarcharType.of(None))))
    conn.create_table("t", schema, partitioned_by=("ds",))
    conn.append("t", [[1, 2, 3], ["a", "b", "c"], ["x", "x", "y"]])
    # layout: one directory per partition value
    assert sorted(os.listdir(os.path.join(wh, "t"))) == ["ds=x", "ds=y"]
    e = Engine()
    e.register_catalog("hive", conn)
    s = e.create_session("hive")
    r = e.execute_sql("select id, name, ds from t order by id", s).to_pandas()
    assert r.values.tolist() == [[1, "a", "x"], [2, "b", "x"], [3, "c", "y"]]


@pytest.fixture()
def delta_wh(tmp_path):
    wh = str(tmp_path / "dwh")
    tdir = os.path.join(wh, "sales")
    _write_parquet(os.path.join(tdir, "part-a.parquet"),
                   {"id": [1, 2], "amount": [10.0, 20.0]})
    _write_parquet(os.path.join(tdir, "part-b.parquet"),
                   {"id": [3], "amount": [30.0]})
    _write_parquet(os.path.join(tdir, "part-stale.parquet"),
                   {"id": [99], "amount": [99.0]})
    schema_string = json.dumps({
        "type": "struct",
        "fields": [
            {"name": "id", "type": "long", "nullable": True, "metadata": {}},
            {"name": "amount", "type": "double", "nullable": True,
             "metadata": {}},
            {"name": "ds", "type": "date", "nullable": True, "metadata": {}},
        ]})
    log = os.path.join(tdir, "_delta_log")
    os.makedirs(log)

    def commit(version, actions):
        with open(os.path.join(log, f"{version:020d}.json"), "w") as f:
            f.write("\n".join(json.dumps(a) for a in actions))

    commit(0, [
        {"protocol": {"minReaderVersion": 1}},
        {"metaData": {"id": "m1", "schemaString": schema_string,
                      "partitionColumns": ["ds"], "format": {"provider":
                                                             "parquet"}}},
        {"add": {"path": "part-a.parquet", "dataChange": True,
                 "partitionValues": {"ds": "2024-01-01"},
                 "stats": json.dumps({"numRecords": 2,
                                      "minValues": {"id": 1},
                                      "maxValues": {"id": 2}})}},
        {"add": {"path": "part-stale.parquet", "dataChange": True,
                 "partitionValues": {"ds": "2024-01-01"}}},
    ])
    commit(1, [
        {"remove": {"path": "part-stale.parquet", "dataChange": True}},
        {"add": {"path": "part-b.parquet", "dataChange": True,
                 "partitionValues": {"ds": "2024-01-02"},
                 "stats": json.dumps({"numRecords": 1,
                                      "minValues": {"id": 3},
                                      "maxValues": {"id": 3}})}},
    ])
    return wh


def test_delta_log_replay_and_scan(delta_wh):
    e = Engine()
    e.register_catalog("delta", DeltaConnector(delta_wh))
    s = e.create_session("delta")
    r = e.execute_sql("select id, amount, ds from sales order by id",
                      s).to_pandas()
    # removed file's id=99 must NOT appear (log replay)
    assert r["id"].tolist() == [1, 2, 3]
    import pandas as pd

    assert pd.Timestamp(r["ds"].iloc[2]) == pd.Timestamp("2024-01-02")


def test_delta_partition_and_stats_pruning(delta_wh):
    conn = DeltaConnector(delta_wh)
    splits = conn.splits("sales")
    # date partition: exact single-value ranges in epoch days
    d1 = (datetime.date(2024, 1, 1) - datetime.date(1970, 1, 1)).days
    ranges = sorted(conn.split_range(sp, "ds") for sp in splits)
    assert ranges == [(d1, d1), (d1 + 1, d1 + 1)]
    # add-action stats feed data-column pruning
    id_ranges = sorted(conn.split_range(sp, "id") for sp in splits)
    assert id_ranges == [(1, 2), (3, 3)]

    e = Engine()
    e.register_catalog("delta", DeltaConnector(delta_wh))
    s = e.create_session("delta")
    r = e.execute_sql(
        "select sum(amount) a from sales where ds = date '2024-01-02'",
        s).to_pandas()
    assert float(r.iloc[0, 0]) == 30.0


def test_delta_tables_listing(delta_wh):
    assert DeltaConnector(delta_wh).tables() == ["sales"]


def test_memory_filesystem_roundtrip():
    from trino_tpu.fs import MemoryFileSystem

    fs = MemoryFileSystem()
    fs.write_bytes("/wh/t/_delta_log/x.json", b"{}")
    assert fs.is_dir("/wh/t/_delta_log")
    assert fs.list_dir("/wh/t") == ["_delta_log"]
    assert fs.read_text("/wh/t/_delta_log/x.json") == "{}"
    assert not fs.exists("/wh/t/missing")


def test_delta_checkpoint_replay(tmp_path):
    """A checkpointed (vacuumed) delta log: replay starts at the checkpoint
    parquet, JSON commits after it apply, commits at/before it are absent
    (reference: TransactionLogAccess + _last_checkpoint)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    wh = str(tmp_path / "cwh")
    tdir = os.path.join(wh, "ck")
    _write_parquet(os.path.join(tdir, "a.parquet"),
                   {"id": [1, 2], "v": [1.0, 2.0]})
    _write_parquet(os.path.join(tdir, "b.parquet"), {"id": [3], "v": [3.0]})
    log = os.path.join(tdir, "_delta_log")
    os.makedirs(log)
    schema_string = json.dumps({
        "type": "struct",
        "fields": [
            {"name": "id", "type": "long", "nullable": True, "metadata": {}},
            {"name": "v", "type": "double", "nullable": True, "metadata": {}},
        ]})
    # checkpoint at version 1 holds metaData + the live 'a' file; NO JSON
    # commits exist at or before version 1 (vacuumed away)
    ck_rows = [
        {"metaData": {"id": "m", "schemaString": schema_string,
                      "partitionColumns": []},
         "add": None},
        {"metaData": None,
         "add": {"path": "a.parquet", "partitionValues": [],
                 "stats": json.dumps({"minValues": {"id": 1},
                                      "maxValues": {"id": 2}})}},
    ]
    pq.write_table(pa.Table.from_pylist(ck_rows),
                   os.path.join(log, f"{1:020d}.checkpoint.parquet"))
    with open(os.path.join(log, "_last_checkpoint"), "w") as f:
        f.write(json.dumps({"version": 1}))
    # commit 2 (after the checkpoint) adds file 'b'
    with open(os.path.join(log, f"{2:020d}.json"), "w") as f:
        f.write(json.dumps({"add": {"path": "b.parquet", "dataChange": True,
                                    "partitionValues": {}}}))

    from trino_tpu import Engine

    e = Engine()
    e.register_catalog("delta", DeltaConnector(wh))
    s = e.create_session("delta")
    r = e.execute_sql("select id, v from ck order by id", s).to_pandas()
    assert r["id"].tolist() == [1, 2, 3]


def test_hive_sql_partitioned_create_table(tmp_path):
    """CREATE TABLE ... WITH (partitioned_by = ARRAY[...]) through SQL: the
    declared schema serves reads before any data lands, INSERTs route rows to
    key=value partition directories."""
    from trino_tpu import Engine

    wh = str(tmp_path / "sqlwh")
    e = Engine()
    e.register_catalog("hive", HiveConnector(wh))
    s = e.create_session("hive")
    e.execute_sql("create table ev (id bigint, v double, ds varchar) "
                  "with (partitioned_by = array['ds'])", s)
    # pending table reads as empty with its declared schema
    r = e.execute_sql("select count(*) c from ev", s).to_pandas()
    assert int(r.iloc[0, 0]) == 0
    e.execute_sql("insert into ev values (1, 1.5, 'a'), (2, 2.5, 'b'), "
                  "(3, 3.5, 'a')", s)
    r = e.execute_sql("select ds, count(*) c, sum(v) sv from ev "
                      "group by ds order by ds", s).to_pandas()
    assert r.values.tolist() == [["a", 2, 5.0], ["b", 1, 2.5]]
    assert sorted(os.listdir(os.path.join(wh, "ev"))) == ["ds=a", "ds=b"]
    # unknown properties reject loudly
    import pytest as _pytest

    with _pytest.raises(ValueError, match="unsupported table properties"):
        e.execute_sql("create table z (a bigint) with (bogus = 1)", s)


def test_hive_plain_create_and_partition_order_guard(tmp_path):
    from trino_tpu import Engine

    wh = str(tmp_path / "pwh")
    e = Engine()
    e.register_catalog("hive", HiveConnector(wh))
    s = e.create_session("hive")
    # plain CREATE TABLE (no partitioning) works, incl. IF NOT EXISTS
    e.execute_sql("create table plain (a bigint, b varchar)", s)
    e.execute_sql("create table if not exists plain (a bigint, b varchar)", s)
    e.execute_sql("insert into plain values (1, 'x')", s)
    r = e.execute_sql("select a, b from plain", s).to_pandas()
    assert r.values.tolist() == [[1, "x"]]
    # non-trailing partition columns reject loudly (discovery appends them
    # last; accepting would flip positional meaning at the first write)
    import pytest as _pytest

    with _pytest.raises(ValueError, match="trailing"):
        e.execute_sql("create table bad (ds varchar, id bigint) "
                      "with (partitioned_by = array['ds'])", s)
    with _pytest.raises(ValueError, match="ARRAY"):
        e.execute_sql("create table bad2 (id bigint, ds varchar) "
                      "with (partitioned_by = 'ds')", s)
