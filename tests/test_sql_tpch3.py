"""TPC-H queries batch 3 (Q2, Q9, Q13, Q15, Q16, Q17, Q20, Q21, Q22) vs pandas oracles:
multi-match joins, left outer join, correlated EXISTS with non-equi residuals,
count(distinct), substring over dictionaries, views."""

import numpy as np
import pandas as pd

from tests.test_sql_tpch import assert_frames_close, dcol, run, D


def _round_half_up(x, scale=2):
    """Decimal HALF_UP rounding at `scale`, matching the engine's decimal avg."""
    f = 10 ** scale
    return np.sign(x) * np.floor(np.abs(x) * f + 0.5) / f


def test_q2(engine, tpch_pandas):
    got = run(engine, """
        select s_acctbal, s_name, n_name, p_partkey, p_mfgr, s_address, s_phone, s_comment
        from part, supplier, partsupp, nation, region
        where p_partkey = ps_partkey and s_suppkey = ps_suppkey and p_size = 15
          and p_type like '%BRASS' and s_nationkey = n_nationkey
          and n_regionkey = r_regionkey and r_name = 'EUROPE'
          and ps_supplycost = (
              select min(ps_supplycost) from partsupp, supplier, nation, region
              where p_partkey = ps_partkey and s_suppkey = ps_suppkey
                and s_nationkey = n_nationkey and n_regionkey = r_regionkey
                and r_name = 'EUROPE')
        order by s_acctbal desc, n_name, s_name, p_partkey
        limit 100""")
    t = tpch_pandas
    j = (t["partsupp"].merge(t["supplier"], left_on="ps_suppkey", right_on="s_suppkey")
         .merge(t["nation"], left_on="s_nationkey", right_on="n_nationkey")
         .merge(t["region"], left_on="n_regionkey", right_on="r_regionkey"))
    eu = j[j.r_name == "EUROPE"]
    mins = eu.groupby("ps_partkey").agg(minc=("ps_supplycost", "min"))
    full = eu.merge(t["part"], left_on="ps_partkey", right_on="p_partkey")
    full = full[(full.p_size == 15) & full.p_type.str.endswith("BRASS")]
    full = full.merge(mins, left_on="p_partkey", right_index=True)
    full = full[full.ps_supplycost == full.minc]
    exp = (full[["s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr", "s_address",
                 "s_phone", "s_comment"]]
           .sort_values(["s_acctbal", "n_name", "s_name", "p_partkey"],
                        ascending=[False, True, True, True])
           .head(100).reset_index(drop=True))
    assert_frames_close(got, exp)


def test_q9(engine, tpch_pandas):
    got = run(engine, """
        select nation, o_year, sum(amount) as sum_profit
        from (select n_name as nation, extract(year from o_orderdate) as o_year,
                     l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity
                         as amount
              from part, supplier, lineitem, partsupp, orders, nation
              where s_suppkey = l_suppkey and ps_suppkey = l_suppkey
                and ps_partkey = l_partkey and p_partkey = l_partkey
                and o_orderkey = l_orderkey and s_nationkey = n_nationkey
                and p_name like '%green%') as profit
        group by nation, o_year
        order by nation, o_year desc""")
    t = tpch_pandas
    p2 = t["part"][t["part"].p_name.str.contains("green")]
    j = (t["lineitem"].merge(p2, left_on="l_partkey", right_on="p_partkey")
         .merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
         .merge(t["partsupp"], left_on=["l_partkey", "l_suppkey"],
                right_on=["ps_partkey", "ps_suppkey"])
         .merge(t["orders"], left_on="l_orderkey", right_on="o_orderkey")
         .merge(t["nation"], left_on="s_nationkey", right_on="n_nationkey")).copy()
    j["o_year"] = dcol(j, "o_orderdate").astype("datetime64[Y]").astype(int) + 1970
    j["amount"] = j.l_extendedprice * (1 - j.l_discount) - j.ps_supplycost * j.l_quantity
    exp = (j.groupby(["n_name", "o_year"], as_index=False)
           .agg(sum_profit=("amount", "sum"))
           .rename(columns={"n_name": "nation"})
           .sort_values(["nation", "o_year"], ascending=[True, False])
           .reset_index(drop=True))
    assert_frames_close(got, exp, rtol=1e-9)


def test_q13(engine, tpch_pandas):
    got = run(engine, """
        select c_count, count(*) as custdist
        from (select c_custkey, count(o_orderkey) as c_count
              from customer left outer join orders on c_custkey = o_custkey
                   and o_comment not like '%special%requests%'
              group by c_custkey) as c_orders (c_custkey, c_count)
        group by c_count
        order by custdist desc, c_count desc""")
    t = tpch_pandas
    o2 = t["orders"][~t["orders"].o_comment.str.match(".*special.*requests.*")]
    j = t["customer"].merge(o2, left_on="c_custkey", right_on="o_custkey", how="left")
    cc = j.groupby("c_custkey").agg(c_count=("o_orderkey", "count"))
    exp = (cc.groupby("c_count", as_index=False).size()
           .rename(columns={"size": "custdist"})
           .sort_values(["custdist", "c_count"], ascending=[False, False])
           .reset_index(drop=True))
    assert_frames_close(got, exp)


def test_q15(engine, tpch_pandas):
    engine.execute_sql("""
        create view revenue0 as
            select l_suppkey as supplier_no,
                   sum(l_extendedprice * (1 - l_discount)) as total_revenue
            from lineitem
            where l_shipdate >= date '1996-01-01' and l_shipdate < date '1996-04-01'
            group by l_suppkey""")
    try:
        got = run(engine, """
            select s_suppkey, s_name, s_address, s_phone, total_revenue
            from supplier, revenue0
            where s_suppkey = supplier_no
              and total_revenue = (select max(total_revenue) from revenue0)
            order by s_suppkey""")
    finally:
        engine.execute_sql("drop view revenue0")
    t = tpch_pandas
    li = t["lineitem"]
    li2 = li[(dcol(li, "l_shipdate") >= D("1996-01-01"))
             & (dcol(li, "l_shipdate") < D("1996-04-01"))].copy()
    li2["rev"] = li2.l_extendedprice * (1 - li2.l_discount)
    rev = li2.groupby("l_suppkey", as_index=False).agg(total_revenue=("rev", "sum"))
    top = rev[rev.total_revenue == rev.total_revenue.max()]
    exp = (top.merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
           [["s_suppkey", "s_name", "s_address", "s_phone", "total_revenue"]]
           .sort_values("s_suppkey").reset_index(drop=True))
    assert_frames_close(got, exp, rtol=1e-9)


def test_q16(engine, tpch_pandas):
    got = run(engine, """
        select p_brand, p_type, p_size, count(distinct ps_suppkey) as supplier_cnt
        from partsupp, part
        where p_partkey = ps_partkey and p_brand <> 'Brand#45'
          and p_type not like 'MEDIUM POLISHED%'
          and p_size in (49, 14, 23, 45, 19, 3, 36, 9)
          and ps_suppkey not in (select s_suppkey from supplier
                                 where s_comment like '%Customer%Complaints%')
        group by p_brand, p_type, p_size
        order by supplier_cnt desc, p_brand, p_type, p_size""")
    t = tpch_pandas
    bad = t["supplier"][t["supplier"].s_comment.str.match(
        ".*Customer.*Complaints.*")].s_suppkey
    p2 = t["part"][(t["part"].p_brand != "Brand#45")
                   & ~t["part"].p_type.str.match("MEDIUM POLISHED.*")
                   & t["part"].p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])]
    j = t["partsupp"].merge(p2, left_on="ps_partkey", right_on="p_partkey")
    j = j[~j.ps_suppkey.isin(bad)]
    exp = (j.groupby(["p_brand", "p_type", "p_size"], as_index=False)
           .agg(supplier_cnt=("ps_suppkey", "nunique"))
           .sort_values(["supplier_cnt", "p_brand", "p_type", "p_size"],
                        ascending=[False, True, True, True])
           .reset_index(drop=True))
    exp = exp[["p_brand", "p_type", "p_size", "supplier_cnt"]]
    assert_frames_close(got, exp)


def test_q17(engine, tpch_pandas):
    got = run(engine, """
        select sum(l_extendedprice) / 7.0 as avg_yearly
        from lineitem, part
        where p_partkey = l_partkey and p_brand = 'Brand#23' and p_container = 'MED BOX'
          and l_quantity < (select 0.2 * avg(l_quantity) from lineitem
                            where l_partkey = p_partkey)""")
    t = tpch_pandas
    li = t["lineitem"]
    # engine's decimal avg rounds HALF_UP at the input scale (2)
    avgq = _round_half_up(li.groupby("l_partkey").l_quantity.mean(), 2)
    p2 = t["part"][(t["part"].p_brand == "Brand#23") & (t["part"].p_container == "MED BOX")]
    j = li.merge(p2, left_on="l_partkey", right_on="p_partkey")
    j = j.merge(avgq.rename("avgq"), left_on="l_partkey", right_index=True)
    sel = j[j.l_quantity < 0.2 * j.avgq]
    exp = sel.l_extendedprice.sum() / 7.0
    np.testing.assert_allclose(got["avg_yearly"][0], exp, rtol=1e-9)


def test_q20(engine, tpch_pandas):
    got = run(engine, """
        select s_name, s_address
        from supplier, nation
        where s_suppkey in (
              select ps_suppkey from partsupp
              where ps_partkey in (select p_partkey from part
                                   where p_name like 'forest%')
                and ps_availqty > (
                    select 0.5 * sum(l_quantity) from lineitem
                    where l_partkey = ps_partkey and l_suppkey = ps_suppkey
                      and l_shipdate >= date '1994-01-01'
                      and l_shipdate < date '1994-01-01' + interval '1' year))
          and s_nationkey = n_nationkey and n_name = 'CANADA'
        order by s_name""")
    t = tpch_pandas
    fparts = t["part"][t["part"].p_name.str.startswith("forest")].p_partkey
    li = t["lineitem"]
    li2 = li[(dcol(li, "l_shipdate") >= D("1994-01-01"))
             & (dcol(li, "l_shipdate") < D("1995-01-01"))]
    sums = li2.groupby(["l_partkey", "l_suppkey"]).agg(q=("l_quantity", "sum"))
    ps = t["partsupp"][t["partsupp"].ps_partkey.isin(fparts)]
    ps = ps.merge(sums, left_on=["ps_partkey", "ps_suppkey"], right_index=True)
    good = ps[ps.ps_availqty > 0.5 * ps.q].ps_suppkey.unique()
    s2 = (t["supplier"].merge(t["nation"], left_on="s_nationkey", right_on="n_nationkey"))
    s2 = s2[(s2.n_name == "CANADA") & s2.s_suppkey.isin(good)]
    exp = s2[["s_name", "s_address"]].sort_values("s_name").reset_index(drop=True)
    assert_frames_close(got, exp)


def test_q21(engine, tpch_pandas):
    got = run(engine, """
        select s_name, count(*) as numwait
        from supplier, lineitem l1, orders, nation
        where s_suppkey = l1.l_suppkey and o_orderkey = l1.l_orderkey
          and o_orderstatus = 'F' and l1.l_receiptdate > l1.l_commitdate
          and exists (select * from lineitem l2
                      where l2.l_orderkey = l1.l_orderkey
                        and l2.l_suppkey <> l1.l_suppkey)
          and not exists (select * from lineitem l3
                          where l3.l_orderkey = l1.l_orderkey
                            and l3.l_suppkey <> l1.l_suppkey
                            and l3.l_receiptdate > l3.l_commitdate)
          and s_nationkey = n_nationkey and n_name = 'SAUDI ARABIA'
        group by s_name
        order by numwait desc, s_name
        limit 100""")
    t = tpch_pandas
    li = t["lineitem"]
    l1 = li[dcol(li, "l_receiptdate") > dcol(li, "l_commitdate")]
    j = (l1.merge(t["supplier"], left_on="l_suppkey", right_on="s_suppkey")
         .merge(t["orders"], left_on="l_orderkey", right_on="o_orderkey")
         .merge(t["nation"], left_on="s_nationkey", right_on="n_nationkey"))
    j = j[(j.o_orderstatus == "F") & (j.n_name == "SAUDI ARABIA")]
    grp = li.groupby("l_orderkey").l_suppkey
    other = (grp.nunique() > 1).rename("has_other").to_frame()
    mn = grp.min().rename("mn")
    other["mn"] = mn
    j = j.merge(other, left_on="l_orderkey", right_index=True)
    # exists l2: some other supplier in the order
    exists2 = j.has_other | (j.mn != j.l_suppkey)
    late = li[dcol(li, "l_receiptdate") > dcol(li, "l_commitdate")]
    lgrp = late.groupby("l_orderkey").l_suppkey
    lother = (lgrp.nunique() > 1).rename("lhas").to_frame()
    lother["lmn"] = lgrp.min().rename("lmn")
    j = j.merge(lother, left_on="l_orderkey", right_index=True, how="left")
    exists3 = j.lhas.fillna(False).astype(bool) | (
        j.lmn.notna() & (j.lmn != j.l_suppkey))
    sel = j[exists2 & ~exists3]
    exp = (sel.groupby("s_name", as_index=False).size()
           .rename(columns={"size": "numwait"})
           .sort_values(["numwait", "s_name"], ascending=[False, True])
           .head(100).reset_index(drop=True))
    assert_frames_close(got, exp)


def test_q22(engine, tpch_pandas):
    got = run(engine, """
        select cntrycode, count(*) as numcust, sum(c_acctbal) as totacctbal
        from (select substring(c_phone, 1, 2) as cntrycode, c_acctbal
              from customer
              where substring(c_phone, 1, 2) in ('13', '31', '23', '29', '30', '18', '17')
                and c_acctbal > (select avg(c_acctbal) from customer
                                 where c_acctbal > 0.00
                                   and substring(c_phone, 1, 2) in
                                       ('13', '31', '23', '29', '30', '18', '17'))
                and not exists (select * from orders
                                where o_custkey = c_custkey)) as custsale
        group by cntrycode
        order by cntrycode""")
    t = tpch_pandas
    c = t["customer"].copy()
    c["cntrycode"] = c.c_phone.str[:2]
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    pool = c[c.cntrycode.isin(codes)]
    # engine's decimal avg rounds HALF_UP at scale 2
    thresh = _round_half_up(pool[pool.c_acctbal > 0].c_acctbal.mean(), 2)
    has_orders = set(t["orders"].o_custkey)
    sel = pool[(pool.c_acctbal > thresh) & ~pool.c_custkey.isin(has_orders)]
    exp = (sel.groupby("cntrycode", as_index=False)
           .agg(numcust=("c_custkey", "size"), totacctbal=("c_acctbal", "sum"))
           .sort_values("cntrycode").reset_index(drop=True))
    assert_frames_close(got, exp, rtol=1e-9)
