"""Extended scalar families: math/bitwise/regexp/url/datetime/string-distance
(reference: operator/scalar/MathFunctions, BitwiseFunctions,
JoniRegexpFunctions, UrlFunctions, DateTimeFunctions test models)."""

import datetime
import math

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector


@pytest.fixture(scope="module")
def feng():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table t (x double, n bigint, s varchar, d date)", s)
    e.execute_sql("""insert into t values
        (1.5, 5, 'http://example.com:8080/a/b?q=1&r=two#frag', date '2024-02-29'),
        (-2.25, 12, 'https://trino.io/docs', date '2021-01-01'),
        (0.5, 255, 'abc-123-xyz', date '2020-12-31')""", s)
    return e, s


def _one(feng, expr, where="n = 5"):
    e, s = feng
    r = e.execute_sql(f"select {expr} v from t where {where}", s).to_pandas()
    return r.iloc[0, 0]


def test_hyperbolic_and_log(feng):
    assert abs(_one(feng, "sinh(x)") - math.sinh(1.5)) < 1e-12
    assert abs(_one(feng, "cosh(x)") - math.cosh(1.5)) < 1e-12
    assert abs(_one(feng, "tanh(x)") - math.tanh(1.5)) < 1e-12
    assert abs(_one(feng, "log(2, 8)") - 3.0) < 1e-12
    assert abs(_one(feng, "e()") - math.e) < 1e-12


def test_float_tests_and_truncate(feng):
    assert bool(_one(feng, "is_nan(nan())"))
    assert not bool(_one(feng, "is_finite(infinity())"))
    assert bool(_one(feng, "is_infinite(infinity())"))
    assert _one(feng, "truncate(1.999)") == 1.0
    assert abs(_one(feng, "truncate(1.987, 2)") - 1.98) < 1e-12
    assert abs(_one(feng, "truncate(-1.987, 2)") - (-1.98)) < 1e-12


def test_bitwise_family(feng):
    assert _one(feng, "bitwise_and(n, 3)") == 5 & 3
    assert _one(feng, "bitwise_or(n, 3)") == 5 | 3
    assert _one(feng, "bitwise_xor(n, 3)") == 5 ^ 3
    assert _one(feng, "bitwise_not(n)") == ~5
    assert _one(feng, "bitwise_left_shift(n, 2)") == 20
    assert _one(feng, "bitwise_right_shift(n, 1)") == 2
    # logical shift of a negative value zero-fills
    assert _one(feng, "bitwise_right_shift(-8, 1)") == (2**64 - 8) >> 1
    assert _one(feng, "bitwise_right_shift_arithmetic(-8, 1)") == -4
    assert _one(feng, "bit_count(255, 64)", "n = 255") == 8
    assert _one(feng, "bit_count(-1, 8)") == 8


def test_regexp_family(feng):
    assert _one(feng, "regexp_extract(s, '\\d+')", "n = 255") == "123"
    assert _one(feng, "regexp_extract(s, '([a-z]+)-(\\d+)', 2)",
                "n = 255") == "123"
    # no match -> NULL
    v = _one(feng, "regexp_extract(s, 'ZZZ')", "n = 255")
    assert v is None or (isinstance(v, float) and np.isnan(v)) or v != v
    assert _one(feng, "regexp_replace(s, '\\d', '#')", "n = 255") == "abc-###-xyz"
    assert _one(feng, "regexp_replace(s, '(\\d+)', '<$1>')",
                "n = 255") == "abc-<123>-xyz"
    assert _one(feng, "regexp_count(s, '\\d')", "n = 255") == 3
    assert _one(feng, "regexp_position(s, '1')", "n = 255") == 5
    assert _one(feng, "regexp_position(s, 'ZZZ')", "n = 255") == -1


def test_string_distance_and_misc(feng):
    assert _one(feng, "levenshtein_distance(s, 'abc-124-xyz')", "n = 255") == 1
    assert _one(feng, "hamming_distance(s, 'abc-124-xyz')", "n = 255") == 1
    assert bool(_one(feng, "ends_with(s, 'xyz')", "n = 255"))
    assert not bool(_one(feng, "ends_with(s, 'abc')", "n = 255"))
    assert _one(feng, "translate(s, 'abc', 'AB')", "n = 255") == "AB-123-xyz"


def test_url_family(feng):
    url = "n = 5"
    assert _one(feng, "url_extract_protocol(s)", url) == "http"
    assert _one(feng, "url_extract_host(s)", url) == "example.com"
    assert _one(feng, "url_extract_port(s)", url) == 8080
    assert _one(feng, "url_extract_path(s)", url) == "/a/b"
    assert _one(feng, "url_extract_query(s)", url) == "q=1&r=two"
    assert _one(feng, "url_extract_fragment(s)", url) == "frag"
    assert _one(feng, "url_extract_parameter(s, 'r')", url) == "two"
    # port absent -> NULL
    v = _one(feng, "url_extract_port(s)", "n = 12")
    assert v is None or v != v
    assert _one(feng, "url_encode('a b&c')", url) == "a+b%26c"
    assert _one(feng, "url_decode('a+b%26c')", url) == "a b&c"


def test_datetime_breadth(feng):
    import pandas as pd

    assert pd.Timestamp(_one(feng, "last_day_of_month(d)")) == \
        pd.Timestamp("2024-02-29")
    assert pd.Timestamp(_one(feng, "last_day_of_month(d)", "n = 12")) == \
        pd.Timestamp("2021-01-31")
    # ISO week boundaries: 2021-01-01 is week 53 of ISO year 2020
    assert _one(feng, "week(d)", "n = 12") == 53
    assert _one(feng, "year_of_week(d)", "n = 12") == 2020
    assert _one(feng, "week_of_year(d)") == \
        datetime.date(2024, 2, 29).isocalendar()[0:2][1]
    assert _one(feng, "yow(d)", "n = 255") == \
        datetime.date(2020, 12, 31).isocalendar()[0]
    assert _one(feng, "day_of_month(d)") == 29
    assert pd.Timestamp(_one(feng, "from_iso8601_date('2023-07-04')")) == \
        pd.Timestamp("2023-07-04")


def test_show_functions_lists_new_families(feng):
    e, s = feng
    r = e.execute_sql("show functions", s).to_pandas()
    names = set(r.iloc[:, 0])
    for n in ("bitwise_and", "regexp_extract", "url_extract_host",
              "levenshtein_distance", "week_of_year", "sinh"):
        assert n in names, n


def test_regexp_replace_dollar_zero_and_backslash(feng):
    assert _one(feng, "regexp_replace(s, '\\d+', '[$0]')",
                "n = 255") == "abc-[123]-xyz"
    with pytest.raises(Exception, match="cannot access group"):
        _one(feng, "regexp_replace(s, '(\\d)', '$9')", "n = 255")


def test_translate_first_mapping_wins(feng):
    assert _one(feng, "translate(s, 'aa', 'bc')", "n = 255") == "bbc-123-xyz"


def test_truncate_negative_scale_and_bad_literals(feng):
    assert _one(feng, "truncate(1987.6, -2)") == 1900.0
    with pytest.raises(Exception, match="integer literal"):
        _one(feng, "truncate(1.9, 1.5)")
