"""Unit tests for the hot-path kernels (reference pattern: operator-level tests driving
operators with synthetic pages, core/trino-main/src/test/.../operator/)."""

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu.ops import hashagg
from trino_tpu.ops.hashing import pack_keys, EMPTY_KEY
from trino_tpu.ops.hashjoin import build_insert, build_table_init, probe
from trino_tpu.page import Page, Schema
from trino_tpu.types import BIGINT, INTEGER, DATE, VarcharType


def test_pack_keys_injective():
    a = jnp.array([1, -5, 1, 7], dtype=jnp.int32)
    b = jnp.array([2, 2, 3, -2], dtype=jnp.int32)
    ranges = [(-5, 7), (-2, 3)]
    packed, exact = pack_keys((a, b), (INTEGER, INTEGER), ranges)
    assert exact
    assert len(set(np.asarray(packed).tolist())) == 4
    packed2, _ = pack_keys((a[:1], b[:1]), (INTEGER, INTEGER), ranges)
    assert packed2[0] == packed[0]
    # without ranges, two 32-bit columns exceed the 62-bit budget -> fingerprint
    _, exact2 = pack_keys((a, b), (INTEGER, INTEGER))
    assert not exact2


def test_groupby_basic():
    keys = jnp.array([3, 1, 3, 1, 3, 9], dtype=jnp.int64)
    vals = jnp.array([10, 20, 30, 40, 50, 60], dtype=jnp.int64)
    valid = jnp.array([True, True, True, True, True, False])
    state = hashagg.groupby_init(16, (jnp.int64,), [(jnp.int64, 0), (jnp.int64, 0)])
    state = hashagg.groupby_insert(
        state, (keys,), (BIGINT,), valid, [(vals, None), (None, None)], ["sum", "count_star"]
    )
    occ, (k,), (s, c) = hashagg.agg_finalize(state)
    occ = np.asarray(occ)
    got = dict(zip(np.asarray(k)[occ].tolist(), np.asarray(s)[occ].tolist()))
    assert got == {3: 90, 1: 60}
    assert not bool(state.overflow)


def test_groupby_overflow_flag():
    n = 64
    keys = jnp.arange(n, dtype=jnp.int64)
    state = hashagg.groupby_init(8, (jnp.int64,), [(jnp.int64, 0)])
    state = hashagg.groupby_insert(
        state, (keys,), (BIGINT,), jnp.ones((n,), bool), [(None, None)], ["count_star"]
    )
    assert bool(state.overflow)


def test_join_build_probe():
    schema = Schema.of(("k", BIGINT), ("v", BIGINT))
    bk = jnp.array([10, 20, 30, 40], dtype=jnp.int64)
    bv = jnp.array([1, 2, 3, 4], dtype=jnp.int64)
    bp = Page.from_arrays(schema, [bk, bv])
    jt = build_table_init(32, bp)
    jt = build_insert(jt, (bk,), (BIGINT,), jnp.ones((4,), bool))
    assert int(jt.dup_count) == 0 and not bool(jt.overflow)
    pk = jnp.array([20, 99, 40, 10], dtype=jnp.int64)
    rows, matched = probe(jt, (pk,), (BIGINT,), jnp.ones((4,), bool))
    np.testing.assert_array_equal(np.asarray(matched), [True, False, True, True])
    got_v = np.asarray(jt.build_columns[1])[np.asarray(rows)]
    np.testing.assert_array_equal(got_v[np.asarray(matched)], [2, 4, 1])


def test_join_duplicate_detection():
    schema = Schema.of(("k", BIGINT),)
    bk = jnp.array([7, 7, 8], dtype=jnp.int64)
    bp = Page.from_arrays(schema, [bk])
    jt = build_table_init(16, bp)
    jt = build_insert(jt, (bk,), (BIGINT,), jnp.ones((3,), bool))
    assert int(jt.dup_count) == 1


def test_groupby_inside_jit_scan():
    """State threading through jit (multi-page accumulation)."""
    state = hashagg.groupby_init(16, (jnp.int64,), [(jnp.int64, 0)])

    @jax.jit
    def step(state, keys):
        return hashagg.groupby_insert(
            state, (keys,), (BIGINT,), jnp.ones(keys.shape, bool), [(keys, None)], ["sum"]
        )

    for chunk in (jnp.array([1, 2, 1], jnp.int64), jnp.array([2, 2, 5], jnp.int64)):
        state = step(state, chunk)
    occ, (k,), (s,) = hashagg.agg_finalize(state)
    occ = np.asarray(occ)
    got = dict(zip(np.asarray(k)[occ].tolist(), np.asarray(s)[occ].tolist()))
    assert got == {1: 2, 2: 6, 5: 5}


def test_partitioned_aggregation_fallback(tpch_sf001, monkeypatch):
    """Group counts beyond the capacity ceiling fall back to Grace-style partitioned
    passes with exact results (was: silent row dropping at MAX_GROUP_CAPACITY)."""
    import trino_tpu.exec.local_executor as LE
    from trino_tpu import Engine

    monkeypatch.setattr(LE, "DEFAULT_GROUP_CAPACITY", 256)
    monkeypatch.setattr(LE, "MAX_GROUP_CAPACITY", 4096)
    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    # 15000 orders -> 15000 groups > 4096 ceiling
    r = e.execute_sql("select o_orderkey, count(*) c from orders group by o_orderkey")
    assert len(r) == 15000
    assert set(r.columns[1].tolist()) == {1}
    r = e.execute_sql(
        "select o_custkey, count(*) c, sum(o_totalprice) s from orders "
        "group by o_custkey order by o_custkey")
    import numpy as np
    assert int(np.sum(r.columns[1])) == 15000


def test_init_multihost_noop_single_host(monkeypatch):
    """Without multi-host configuration, init_multihost is a no-op returning
    False (jax.distributed.initialize must NOT be called single-host)."""
    from trino_tpu.parallel import mesh as M

    for var in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                "JAX_PROCESS_ID", "TPU_WORKER_HOSTNAMES"):
        monkeypatch.delenv(var, raising=False)
    called = []
    monkeypatch.setattr(M.jax.distributed, "initialize",
                        lambda *a, **k: called.append(1))
    assert M.init_multihost() is False
    assert not called
    # explicit multi-host config routes through jax.distributed.initialize
    assert M.init_multihost("10.0.0.1:8476", 2, 0) is True
    assert called
