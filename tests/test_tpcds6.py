"""TPC-DS query breadth, round 5 batch 4: channel-twin shapes of already
covered queries plus zip-prefix intersect joins and cross-channel return
ratios.  Covers q8, q27, q29, q56, q57, q63, q76, q81, q82, q83.
Reference corpus: testing/trino-benchmark-queries/ + plugin/trino-tpcds.

Generator-driven deviations (documented, not hidden): fact foreign keys are
dense (never NULL), so the q76 shape keeps its union-pivot structure with a
value predicate instead of the IS NULL channel slices; ca_zip/s_zip are
INTEGER in this generator, so the q8 zip-prefix logic uses integer division
(Trino int division truncates) instead of substr."""

import numpy as np
import pandas as pd
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpcds import TpcdsConnector

from test_tpcds2 import _table
from test_tpcds3 import _check

SF = 0.01


@pytest.fixture(scope="module")
def eng():
    e = Engine()
    e.register_catalog("tpcds", TpcdsConnector(sf=SF, split_rows=1 << 14))
    return e, e.create_session("tpcds")


@pytest.fixture(scope="module")
def host(eng):
    e, _ = eng
    conn = e.catalogs["tpcds"]
    return {
        "store_sales": _table(conn, "store_sales", [
            "ss_sold_date_sk", "ss_item_sk", "ss_store_sk", "ss_customer_sk",
            "ss_cdemo_sk", "ss_ticket_number", "ss_quantity",
            "ss_ext_sales_price", "ss_net_profit", "ss_coupon_amt",
            "ss_list_price"]),
        "store_returns": _table(conn, "store_returns", [
            "sr_returned_date_sk", "sr_item_sk", "sr_customer_sk",
            "sr_ticket_number", "sr_return_quantity"]),
        "catalog_sales": _table(conn, "catalog_sales", [
            "cs_sold_date_sk", "cs_item_sk", "cs_bill_customer_sk",
            "cs_call_center_sk", "cs_ext_sales_price", "cs_quantity"]),
        "catalog_returns": _table(conn, "catalog_returns", [
            "cr_returned_date_sk", "cr_item_sk", "cr_returning_customer_sk",
            "cr_returning_addr_sk", "cr_return_amt_inc_tax",
            "cr_return_quantity"]),
        "web_sales": _table(conn, "web_sales", [
            "ws_sold_date_sk", "ws_item_sk", "ws_bill_customer_sk",
            "ws_ext_sales_price", "ws_quantity"]),
        "web_returns": _table(conn, "web_returns", [
            "wr_returned_date_sk", "wr_item_sk", "wr_return_quantity"]),
        "item": _table(conn, "item", [
            "i_item_sk", "i_item_id", "i_brand_id", "i_color",
            "i_manufact_id", "i_manager_id", "i_category", "i_class",
            "i_current_price"]),
        "date_dim": _table(conn, "date_dim", [
            "d_date_sk", "d_year", "d_moy", "d_qoy"]),
        "store": _table(conn, "store", [
            "s_store_sk", "s_store_name", "s_state", "s_zip"]),
        "customer": _table(conn, "customer", [
            "c_customer_sk", "c_customer_id", "c_current_addr_sk",
            "c_preferred_cust_flag"]),
        "customer_address": _table(conn, "customer_address", [
            "ca_address_sk", "ca_state", "ca_zip"]),
        "customer_demographics": _table(conn, "customer_demographics", [
            "cd_demo_sk", "cd_gender", "cd_marital_status",
            "cd_education_status"]),
        "call_center": _table(conn, "call_center", [
            "cc_call_center_sk", "cc_name"]),
        "inventory": _table(conn, "inventory", [
            "inv_item_sk", "inv_quantity_on_hand"]),
    }


def test_q27_demographic_rollup(eng, host):
    """Q27 shape: demographic-filtered averages under rollup(item, state)."""
    e, s = eng
    got = e.execute_sql("""
        select i_item_id, s_state,
               grouping(i_item_id, s_state) lvl,
               avg(ss_quantity) agg1, sum(ss_coupon_amt) agg3
        from store_sales, customer_demographics, date_dim, store, item
        where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
          and ss_store_sk = s_store_sk and ss_cdemo_sk = cd_demo_sk
          and cd_gender = 'M' and cd_marital_status = 'S'
          and cd_education_status = 'College' and d_year = 2000
        group by rollup (i_item_id, s_state)
        order by lvl desc, i_item_id, s_state limit 60""", s).to_pandas()
    ss, cd, dd = (host["store_sales"], host["customer_demographics"],
                  host["date_dim"])
    st, it = host["store"], host["item"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk") \
        .merge(it, left_on="ss_item_sk", right_on="i_item_sk") \
        .merge(st, left_on="ss_store_sk", right_on="s_store_sk") \
        .merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
    j = j[(j.cd_gender == "M") & (j.cd_marital_status == "S")
          & (j.cd_education_status == "College") & (j.d_year == 2000)]
    pairs = j.groupby(["i_item_id", "s_state"], as_index=False).agg(
        agg1=("ss_quantity", "mean"), agg3=("ss_coupon_amt", "sum"))
    pairs["lvl"] = 0
    byitem = j.groupby("i_item_id", as_index=False).agg(
        agg1=("ss_quantity", "mean"), agg3=("ss_coupon_amt", "sum"))
    byitem["s_state"] = None
    byitem["lvl"] = 1
    total = pd.DataFrame({"i_item_id": [None], "s_state": [None], "lvl": [3],
                          "agg1": [j.ss_quantity.mean()],
                          "agg3": [j.ss_coupon_amt.sum()]})
    ref = pd.concat([total, byitem, pairs], ignore_index=True)
    ref = ref.sort_values(
        ["lvl", "i_item_id", "s_state"], ascending=[False, True, True],
        key=lambda c: c if c.name == "lvl" else pd.Categorical(
            c.fillna("￿"))).head(60).reset_index(drop=True)
    assert got["i_item_id"].fillna("~").tolist() == \
        ref["i_item_id"].fillna("~").tolist()
    assert got["s_state"].fillna("~").tolist() == \
        ref["s_state"].fillna("~").tolist()
    assert got["lvl"].tolist() == ref["lvl"].tolist()
    np.testing.assert_allclose(got.agg1.astype(float),
                               ref.agg1.astype(float), rtol=1e-9)
    np.testing.assert_allclose(got.agg3.astype(float),
                               ref.agg3.astype(float), rtol=1e-9)


def test_q29_quantity_flow_three_channels(eng, host):
    """Q29 shape: quantity flow store-sale -> store-return -> catalog
    re-purchase with per-channel date windows (three date_dim aliases)."""
    e, s = eng
    got = e.execute_sql("""
        select i_item_id, sum(ss_quantity) store_qty,
               sum(sr_return_quantity) return_qty,
               sum(cs_quantity) catalog_qty
        from store_sales, store_returns, catalog_sales, item,
             date_dim d1, date_dim d2, date_dim d3
        where ss_customer_sk = sr_customer_sk and ss_item_sk = sr_item_sk
          and ss_ticket_number = sr_ticket_number
          and sr_customer_sk = cs_bill_customer_sk and sr_item_sk = cs_item_sk
          and ss_item_sk = i_item_sk
          and ss_sold_date_sk = d1.d_date_sk and d1.d_year = 1999
          and d1.d_moy = 4
          and sr_returned_date_sk = d2.d_date_sk and d2.d_year = 1999
          and d2.d_moy between 4 and 7
          and cs_sold_date_sk = d3.d_date_sk
          and d3.d_year in (1999, 2000, 2001)
        group by i_item_id order by i_item_id limit 50""", s).to_pandas()
    ss, sr, cs, it, dd = (host["store_sales"], host["store_returns"],
                          host["catalog_sales"], host["item"],
                          host["date_dim"])
    d1 = dd[(dd.d_year == 1999) & (dd.d_moy == 4)]
    d2 = dd[(dd.d_year == 1999) & dd.d_moy.between(4, 7)]
    d3 = dd[dd.d_year.isin([1999, 2000, 2001])]
    j = ss.merge(sr, left_on=["ss_customer_sk", "ss_item_sk",
                              "ss_ticket_number"],
                 right_on=["sr_customer_sk", "sr_item_sk",
                           "sr_ticket_number"]) \
        .merge(cs, left_on=["sr_customer_sk", "sr_item_sk"],
               right_on=["cs_bill_customer_sk", "cs_item_sk"]) \
        .merge(it, left_on="ss_item_sk", right_on="i_item_sk") \
        .merge(d1[["d_date_sk"]], left_on="ss_sold_date_sk",
               right_on="d_date_sk") \
        .merge(d2[["d_date_sk"]], left_on="sr_returned_date_sk",
               right_on="d_date_sk", suffixes=("", "_r")) \
        .merge(d3[["d_date_sk"]], left_on="cs_sold_date_sk",
               right_on="d_date_sk", suffixes=("", "_c"))
    ref = j.groupby("i_item_id", as_index=False).agg(
        store_qty=("ss_quantity", "sum"),
        return_qty=("sr_return_quantity", "sum"),
        catalog_qty=("cs_quantity", "sum")) \
        .sort_values("i_item_id").head(50).reset_index(drop=True)
    _check(got, ref, set())


def test_q56_color_items_three_channel_union(eng, host):
    """Q56 shape: per-item revenue over a colour-selected item set, summed
    across the three channel subqueries (q33's manufact twin)."""
    e, s = eng
    got = e.execute_sql("""
        select i_item_id, sum(total_sales) total_sales from (
          select i_item_id, sum(ss_ext_sales_price) total_sales
          from store_sales, date_dim, item
          where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
            and d_year = 2000 and d_moy = 2
            and i_item_id in (select i_item_id from item
                              where i_color in ('red', 'green', 'blue'))
          group by i_item_id
          union all
          select i_item_id, sum(cs_ext_sales_price) total_sales
          from catalog_sales, date_dim, item
          where cs_sold_date_sk = d_date_sk and cs_item_sk = i_item_sk
            and d_year = 2000 and d_moy = 2
            and i_item_id in (select i_item_id from item
                              where i_color in ('red', 'green', 'blue'))
          group by i_item_id
          union all
          select i_item_id, sum(ws_ext_sales_price) total_sales
          from web_sales, date_dim, item
          where ws_sold_date_sk = d_date_sk and ws_item_sk = i_item_sk
            and d_year = 2000 and d_moy = 2
            and i_item_id in (select i_item_id from item
                              where i_color in ('red', 'green', 'blue'))
          group by i_item_id) x
        group by i_item_id
        order by total_sales desc, i_item_id limit 40""", s).to_pandas()
    dd, it = host["date_dim"], host["item"]
    sel_ids = set(it[it.i_color.isin(["red", "green", "blue"])].i_item_id)
    frames = []
    for t, dk, ik, v in (("store_sales", "ss_sold_date_sk", "ss_item_sk",
                          "ss_ext_sales_price"),
                         ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                          "cs_ext_sales_price"),
                         ("web_sales", "ws_sold_date_sk", "ws_item_sk",
                          "ws_ext_sales_price")):
        j = host[t].merge(dd, left_on=dk, right_on="d_date_sk") \
            .merge(it, left_on=ik, right_on="i_item_sk")
        j = j[(j.d_year == 2000) & (j.d_moy == 2)
              & j.i_item_id.isin(sel_ids)]
        frames.append(j.groupby("i_item_id", as_index=False)[v].sum()
                      .rename(columns={v: "total_sales"}))
    u = pd.concat(frames, ignore_index=True)
    ref = u.groupby("i_item_id", as_index=False).total_sales.sum() \
        .sort_values(["total_sales", "i_item_id"],
                     ascending=[False, True]).head(40).reset_index(drop=True)
    _check(got, ref, {"total_sales"})


def test_q57_call_center_brand_vs_average(eng, host):
    """Q57 shape: catalog-channel monthly brand sums per call center vs the
    center+brand window average (q47's catalog twin)."""
    e, s = eng
    got = e.execute_sql("""
        with v1 as (
          select cc_name, i_brand_id brand, d_moy moy,
                 sum(cs_ext_sales_price) msum
          from catalog_sales, item, date_dim, call_center
          where cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk
            and cs_call_center_sk = cc_call_center_sk and d_year = 2000
          group by cc_name, i_brand_id, d_moy)
        select cc_name, brand, moy, msum,
               avg(msum) over (partition by cc_name, brand) avg_monthly
        from v1 order by cc_name, brand, moy limit 80""", s).to_pandas()
    cs, it, dd, cc = (host["catalog_sales"], host["item"], host["date_dim"],
                      host["call_center"])
    j = cs.merge(it, left_on="cs_item_sk", right_on="i_item_sk") \
        .merge(dd, left_on="cs_sold_date_sk", right_on="d_date_sk") \
        .merge(cc, left_on="cs_call_center_sk", right_on="cc_call_center_sk")
    j = j[j.d_year == 2000]
    v1 = j.groupby(["cc_name", "i_brand_id", "d_moy"], as_index=False) \
        .cs_ext_sales_price.sum().rename(columns={
            "i_brand_id": "brand", "d_moy": "moy",
            "cs_ext_sales_price": "msum"})
    v1["avg_monthly"] = v1.groupby(["cc_name", "brand"]) \
        .msum.transform("mean")
    ref = v1.sort_values(["cc_name", "brand", "moy"]).head(80) \
        .reset_index(drop=True)
    for c in ("cc_name", "brand", "moy"):
        assert list(got[c]) == list(ref[c]), c
    np.testing.assert_allclose(got.msum.astype(float), ref.msum.astype(float),
                               rtol=1e-9)
    np.testing.assert_allclose(got.avg_monthly.astype(float),
                               ref.avg_monthly.astype(float), atol=0.0051)


def test_q63_manager_window_avg(eng, host):
    """Q63 shape: monthly manager sales vs their yearly window average
    (q53's manager twin)."""
    e, s = eng
    got = e.execute_sql("""
        select i_manager_id, d_moy, sum_sales, avg_monthly
        from (select i_manager_id, d_moy,
                sum(ss_ext_sales_price) sum_sales,
                avg(sum(ss_ext_sales_price))
                  over (partition by i_manager_id) avg_monthly
              from store_sales, item, date_dim
              where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
                and d_year = 2000 and i_manager_id between 1 and 15
              group by i_manager_id, d_moy)
        order by i_manager_id, d_moy limit 60""", s).to_pandas()
    ss, it, dd = host["store_sales"], host["item"], host["date_dim"]
    j = ss.merge(it[(it.i_manager_id >= 1) & (it.i_manager_id <= 15)],
                 left_on="ss_item_sk", right_on="i_item_sk") \
        .merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk",
               right_on="d_date_sk")
    g = j.groupby(["i_manager_id", "d_moy"], as_index=False) \
        .ss_ext_sales_price.sum() \
        .rename(columns={"ss_ext_sales_price": "sum_sales"})
    g["avg_monthly"] = np.floor(g.groupby("i_manager_id")
                                .sum_sales.transform("mean") * 100
                                + 0.5) / 100
    ref = g.sort_values(["i_manager_id", "d_moy"]).head(60) \
        .reset_index(drop=True)
    _check(got, ref, {"sum_sales", "avg_monthly"})


def test_q76_channel_union_pivot(eng, host):
    """Q76 shape: UNION ALL of the three channels with literal channel tags,
    count+sum pivoted over (channel, year, quarter, category).  This
    generator's fact FKs are dense (no NULLs), so the channel slices filter
    on small quantities instead of IS NULL keys."""
    e, s = eng
    got = e.execute_sql("""
        select channel, d_year, d_qoy, i_category,
               count(*) sales_cnt, sum(ext_sales_price) sales_amt
        from (
          select 'store' channel, ss_item_sk item_sk,
                 ss_sold_date_sk date_sk, ss_ext_sales_price ext_sales_price
          from store_sales where ss_quantity <= 2
          union all
          select 'web' channel, ws_item_sk item_sk,
                 ws_sold_date_sk date_sk, ws_ext_sales_price ext_sales_price
          from web_sales where ws_quantity <= 2
          union all
          select 'catalog' channel, cs_item_sk item_sk,
                 cs_sold_date_sk date_sk, cs_ext_sales_price ext_sales_price
          from catalog_sales where cs_quantity <= 2) u, item, date_dim
        where item_sk = i_item_sk and date_sk = d_date_sk
        group by channel, d_year, d_qoy, i_category
        order by channel, d_year, d_qoy, i_category limit 60""",
        s).to_pandas()
    it, dd = host["item"], host["date_dim"]
    frames = []
    for name, t, ik, dk, qk, v in (
            ("store", "store_sales", "ss_item_sk", "ss_sold_date_sk",
             "ss_quantity", "ss_ext_sales_price"),
            ("web", "web_sales", "ws_item_sk", "ws_sold_date_sk",
             "ws_quantity", "ws_ext_sales_price"),
            ("catalog", "catalog_sales", "cs_item_sk", "cs_sold_date_sk",
             "cs_quantity", "cs_ext_sales_price")):
        f = host[t]
        f = f[f[qk] <= 2][[ik, dk, v]].rename(columns={
            ik: "item_sk", dk: "date_sk", v: "ext_sales_price"})
        f["channel"] = name
        frames.append(f)
    u = pd.concat(frames, ignore_index=True) \
        .merge(it, left_on="item_sk", right_on="i_item_sk") \
        .merge(dd, left_on="date_sk", right_on="d_date_sk")
    ref = u.groupby(["channel", "d_year", "d_qoy", "i_category"],
                    as_index=False).agg(
        sales_cnt=("ext_sales_price", "size"),
        sales_amt=("ext_sales_price", "sum"))
    ref = ref.sort_values(["channel", "d_year", "d_qoy", "i_category"]) \
        .head(60).reset_index(drop=True)
    _check(got, ref, {"sales_amt"})


def test_q81_catalog_returns_above_state_average(eng, host):
    """Q81 shape: catalog returners above 1.2x their state's average return
    (q30's catalog twin, tax-inclusive amounts)."""
    e, s = eng
    got = e.execute_sql("""
        with ctr as (
          select cr_returning_customer_sk ctr_cust, ca_state ctr_state,
                 sum(cr_return_amt_inc_tax) ctr_ret
          from catalog_returns, date_dim, customer_address
          where cr_returned_date_sk = d_date_sk and d_year = 2000
            and cr_returning_addr_sk = ca_address_sk
          group by cr_returning_customer_sk, ca_state)
        select c_customer_id, ctr_ret
        from ctr, customer
        where ctr_ret > (select avg(ctr_ret) * 1.2 from ctr c2
                         where ctr.ctr_state = c2.ctr_state)
          and ctr_cust = c_customer_sk
        order by c_customer_id limit 50""", s).to_pandas()
    cr, dd, ca, cu = (host["catalog_returns"], host["date_dim"],
                      host["customer_address"], host["customer"])
    j = cr.merge(dd, left_on="cr_returned_date_sk", right_on="d_date_sk")
    j = j[j.d_year == 2000].merge(
        ca, left_on="cr_returning_addr_sk", right_on="ca_address_sk")
    ctr = j.groupby(["cr_returning_customer_sk", "ca_state"],
                    as_index=False).cr_return_amt_inc_tax.sum() \
        .rename(columns={"cr_returning_customer_sk": "cust",
                         "ca_state": "state",
                         "cr_return_amt_inc_tax": "ret"})
    avg = ctr.groupby("state").ret.mean() * 1.2
    ctr = ctr.merge(avg.rename("thresh"), left_on="state", right_index=True)
    ctr = ctr[ctr.ret > ctr.thresh]
    ref = ctr.merge(cu, left_on="cust", right_on="c_customer_sk")
    ref = ref[["c_customer_id", "ret"]].rename(columns={"ret": "ctr_ret"}) \
        .sort_values("c_customer_id").head(50).reset_index(drop=True)
    _check(got, ref, {"ctr_ret"})


def test_q82_store_inventory_price_band(eng, host):
    """Q82 shape: items in a price band in inventory and sold in store
    (q37's store twin)."""
    e, s = eng
    got = e.execute_sql("""
        select i_item_id, i_current_price
        from item, inventory, store_sales
        where i_current_price between 20 and 50
          and inv_item_sk = i_item_sk and ss_item_sk = i_item_sk
          and inv_quantity_on_hand between 100 and 500
        group by i_item_id, i_current_price
        order by i_item_id limit 30""", s).to_pandas()
    it, inv, ss = host["item"], host["inventory"], host["store_sales"]
    sel = it[(it.i_current_price >= 20) & (it.i_current_price <= 50)]
    has_inv = set(inv[(inv.inv_quantity_on_hand >= 100)
                      & (inv.inv_quantity_on_hand <= 500)].inv_item_sk)
    has_ss = set(ss.ss_item_sk)
    sel = sel[sel.i_item_sk.isin(has_inv) & sel.i_item_sk.isin(has_ss)]
    ref = sel.groupby(["i_item_id", "i_current_price"], as_index=False) \
        .size()[["i_item_id", "i_current_price"]]
    ref = ref.sort_values("i_item_id").head(30).reset_index(drop=True)
    _check(got, ref, {"i_current_price"})


def test_q83_return_quantity_ratios(eng, host):
    """Q83 shape: per-item return quantities of the three channels joined on
    item_id with each channel's share of the total."""
    e, s = eng
    got = e.execute_sql("""
        with sr_items as (
          select i_item_id item_id, sum(sr_return_quantity) sr_item_qty
          from store_returns, item, date_dim
          where sr_item_sk = i_item_sk and sr_returned_date_sk = d_date_sk
            and d_year = 2000 and d_moy = 9
          group by i_item_id),
        cr_items as (
          select i_item_id item_id, sum(cr_return_quantity) cr_item_qty
          from catalog_returns, item, date_dim
          where cr_item_sk = i_item_sk and cr_returned_date_sk = d_date_sk
            and d_year = 2000 and d_moy = 9
          group by i_item_id),
        wr_items as (
          select i_item_id item_id, sum(wr_return_quantity) wr_item_qty
          from web_returns, item, date_dim
          where wr_item_sk = i_item_sk and wr_returned_date_sk = d_date_sk
            and d_year = 2000 and d_moy = 9
          group by i_item_id)
        select sr_items.item_id, sr_item_qty,
               sr_item_qty * 1.0
                 / (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0
                 * 100 sr_dev,
               cr_item_qty, wr_item_qty,
               (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 average
        from sr_items, cr_items, wr_items
        where sr_items.item_id = cr_items.item_id
          and sr_items.item_id = wr_items.item_id
        order by sr_items.item_id limit 40""", s).to_pandas()
    it, dd = host["item"], host["date_dim"]
    sel = dd[(dd.d_year == 2000) & (dd.d_moy == 9)][["d_date_sk"]]
    chans = {}
    for key, t, ik, dk, v in (
            ("sr", "store_returns", "sr_item_sk", "sr_returned_date_sk",
             "sr_return_quantity"),
            ("cr", "catalog_returns", "cr_item_sk", "cr_returned_date_sk",
             "cr_return_quantity"),
            ("wr", "web_returns", "wr_item_sk", "wr_returned_date_sk",
             "wr_return_quantity")):
        j = host[t].merge(it, left_on=ik, right_on="i_item_sk") \
            .merge(sel, left_on=dk, right_on="d_date_sk")
        chans[key] = j.groupby("i_item_id", as_index=False)[v].sum() \
            .rename(columns={"i_item_id": "item_id", v: f"{key}_item_qty"})
    ref = chans["sr"].merge(chans["cr"], on="item_id") \
        .merge(chans["wr"], on="item_id")
    tot = ref.sr_item_qty + ref.cr_item_qty + ref.wr_item_qty
    ref["sr_dev"] = ref.sr_item_qty * 1.0 / tot / 3.0 * 100
    ref["average"] = tot / 3.0
    ref = ref[["item_id", "sr_item_qty", "sr_dev", "cr_item_qty",
               "wr_item_qty", "average"]].sort_values("item_id") \
        .head(40).reset_index(drop=True)
    _check(got, ref, {"sr_dev", "average"})


def test_q8_preferred_zip_prefix_profit(eng, host):
    """Q8 shape: store profit restricted to zip prefixes that both appear in
    a fixed prefix window AND have >10 preferred customers (INTERSECT +
    HAVING feeding a prefix equi-join).  ca_zip/s_zip are INTEGER here, so
    prefixes use truncating integer division instead of substr."""
    e, s = eng
    got = e.execute_sql("""
        select s_store_name, sum(ss_net_profit) profit
        from store_sales, date_dim,
             (select s_store_sk, s_store_name, s_zip / 1000 szp
              from store) st,
             (select zp from
                (select ca_zip / 1000 zp from customer_address
                 where ca_zip / 1000 between 10 and 40
                 group by ca_zip / 1000
                 intersect
                 select ca_zip / 1000 zp
                 from customer_address, customer
                 where ca_address_sk = c_current_addr_sk
                   and c_preferred_cust_flag = 'Y'
                 group by ca_zip / 1000
                 having count(*) > 10) z) v
        where ss_store_sk = st.s_store_sk and ss_sold_date_sk = d_date_sk
          and d_qoy = 2 and d_year = 1998 and st.szp = v.zp
        group by s_store_name order by s_store_name limit 20""",
        s).to_pandas()
    ss, dd, st = host["store_sales"], host["date_dim"], host["store"]
    ca, cu = host["customer_address"], host["customer"]
    zp_a = set((ca.ca_zip // 1000)[(ca.ca_zip // 1000).between(10, 40)])
    pref = ca.merge(cu[cu.c_preferred_cust_flag == "Y"],
                    left_on="ca_address_sk", right_on="c_current_addr_sk")
    cnt = (pref.ca_zip // 1000).value_counts()
    zp_b = set(cnt[cnt > 10].index)
    zps = zp_a & zp_b
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk") \
        .merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j = j[(j.d_qoy == 2) & (j.d_year == 1998)
          & (j.s_zip // 1000).isin(zps)]
    ref = j.groupby("s_store_name", as_index=False).ss_net_profit.sum() \
        .rename(columns={"ss_net_profit": "profit"}) \
        .sort_values("s_store_name").head(20).reset_index(drop=True)
    _check(got, ref, {"profit"})
