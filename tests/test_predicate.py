"""TupleDomain / Domain / ValueSet algebra + DomainTranslator + split pruning.

Reference test models: core/trino-spi/src/test/java/io/trino/spi/predicate/
TestTupleDomain.java, TestDomain.java, TestSortedRangeSet.java, and the
DomainTranslator tests in trino-main.
"""

import numpy as np
import pytest

from trino_tpu.spi.predicate import (Domain, EquatableValueSet, Range, SortedRangeSet,
                                     TupleDomain)
from trino_tpu.sql import ir
from trino_tpu.sql.domain_translator import extract_domains, split_conjuncts
from trino_tpu.types import BIGINT, DATE, DOUBLE, VarcharType


def test_range_basics():
    r = Range.between(1, 10)
    assert r.contains_value(1) and r.contains_value(10) and not r.contains_value(11)
    assert Range.greater_than(5).contains_value(6)
    assert not Range.greater_than(5).contains_value(5)
    assert Range.less_than_or_equal(5).contains_value(5)
    with pytest.raises(ValueError):
        Range(5, False, 5, False)
    assert Range.between(1, 5).overlaps(Range.between(5, 9))
    assert not Range.between(1, 5).overlaps(Range.greater_than(5))
    assert Range.between(1, 5).intersect(Range.between(3, 9)) == Range.between(3, 5)
    assert Range.between(1, 5).intersect(Range.greater_than(5)) is None
    assert Range.between(1, 3).span(Range.between(7, 9)) == Range.between(1, 9)


def test_sorted_range_set_normalization():
    s = SortedRangeSet.of(Range.between(5, 9), Range.between(1, 3), Range.between(2, 6))
    assert s.ranges == (Range.between(1, 9),)
    s2 = SortedRangeSet.of(Range.between(1, 2), Range.between(5, 6))
    assert len(s2.ranges) == 2
    # adjacency merges: [1,2] U (2,3] = [1,3]
    s3 = SortedRangeSet.of(Range.between(1, 2), Range(2, False, 3, True))
    assert s3.ranges == (Range.between(1, 3),)


def test_sorted_range_set_ops():
    a = SortedRangeSet.of(Range.between(1, 5), Range.between(10, 20))
    b = SortedRangeSet.of(Range.between(4, 12))
    i = a.intersect(b)
    assert i.ranges == (Range.between(4, 5), Range.between(10, 12))
    u = a.union(b)
    assert u.ranges == (Range.between(1, 20),)
    c = a.complement()
    assert c.contains_value(6) and not c.contains_value(3) and c.contains_value(21)
    assert c.complement().ranges == a.ranges
    assert SortedRangeSet.none().complement().is_all
    assert SortedRangeSet.all_().complement().is_none


def test_sorted_range_set_values_and_bounds():
    s = SortedRangeSet.of_values([3, 1, 2, 3])
    assert s.is_discrete and s.values == [1, 2, 3]
    assert s.bounds() == (1, 3)
    assert SortedRangeSet.of(Range.less_than(5)).bounds() == (None, 5)


def test_equatable_value_set():
    a = EquatableValueSet.of_values([1, 2, 3])
    b = EquatableValueSet.of_values([2, 3, 4])
    assert a.intersect(b).entries == frozenset({2, 3})
    assert a.union(b).entries == frozenset({1, 2, 3, 4})
    nb = b.complement()
    assert a.intersect(nb).entries == frozenset({1})
    assert a.union(nb).complement().entries == frozenset({4})  # union misses only 4
    assert nb.contains_value(9) and not nb.contains_value(2)


def test_domain_algebra():
    d1 = Domain.from_range(Range.between(1, 10))
    d2 = Domain.from_range(Range.between(5, 20))
    assert d1.intersect(d2).values.ranges == (Range.between(5, 10),)
    assert d1.union(d2).values.ranges == (Range.between(1, 20),)
    assert not d1.null_allowed
    nn = Domain.not_null()
    assert d1.intersect(nn).includes_value(5) and not d1.intersect(nn).includes_value(None)
    on = Domain.only_null()
    assert on.includes_value(None) and not on.includes_value(1)
    assert Domain.single_value(7).complement().includes_value(None)
    assert Domain.all_().complement().is_none
    assert d1.overlaps_range(10, 30) and not d1.overlaps_range(11, 30)
    disc = Domain.multiple_values([5, 50], orderable=False)
    assert disc.overlaps_range(40, 60) and not disc.overlaps_range(10, 40)


def test_tuple_domain():
    t1 = TupleDomain.with_column_domains({"a": Domain.from_range(Range.between(1, 10))})
    t2 = TupleDomain.with_column_domains({"a": Domain.from_range(Range.between(5, 20)),
                                          "b": Domain.single_value(3)})
    ti = t1.intersect(t2)
    assert ti.domain("a").values.ranges == (Range.between(5, 10),)
    assert ti.domain("b").is_single_value
    # contradiction -> NONE
    t3 = TupleDomain.with_column_domains({"a": Domain.from_range(Range.between(11, 20))})
    assert t1.intersect(t3).is_none
    assert not t1.overlaps(t3)
    assert t1.overlaps(t2)
    # column-wise union keeps only shared columns
    u = t1.column_wise_union(t2)
    assert u.domain("b") is None
    assert u.domain("a").values.ranges == (Range.between(1, 20),)
    assert t1.includes_row({"a": 5}) and not t1.includes_row({"a": 0})
    assert not t1.includes_row({"a": None})
    # transform_keys merging
    tt = t2.transform_keys(lambda k: "x")
    assert tt.is_none or tt.domain("x") is not None


def test_tuple_domain_equality_hash():
    t1 = TupleDomain.with_column_domains({"a": Domain.single_value(1)})
    t2 = TupleDomain.with_column_domains({"a": Domain.single_value(1)})
    assert t1 == t2 and hash(t1) == hash(t2)
    assert TupleDomain.all_() == TupleDomain({})
    assert TupleDomain.none() == TupleDomain(None)


def _f(idx, ty=BIGINT):
    return ir.FieldRef(idx, ty)


def _c(v, ty=BIGINT):
    return ir.Constant(v, ty)


def _call(op, *args):
    from trino_tpu.types import BOOLEAN

    return ir.Call(op, tuple(args), BOOLEAN)


def test_domain_translator_comparisons():
    conj = [_call("gt", _f(0), _c(5)), _call("lte", _f(0), _c(10)),
            _call("eq", _f(1), _c(3))]
    res = extract_domains(conj)
    td = res.tuple_domain
    assert res.residuals == []
    assert td.domain(0).values.ranges == (Range(5, False, 10, True),)
    assert td.domain(1).is_single_value
    # flipped constant-first comparison
    res2 = extract_domains([_call("lt", _c(5), _f(0))])
    assert res2.tuple_domain.domain(0).values.ranges == (Range.greater_than(5),)


def test_domain_translator_between_in_null():
    res = extract_domains([
        _call("between", _f(0), _c(1), _c(9)),
        _call("in", _f(1), _c(2), _c(4), _c(6)),
        _call("not", _call("is_null", _f(2))),
    ])
    td = res.tuple_domain
    assert td.domain(0).values.ranges == (Range.between(1, 9),)
    assert td.domain(1).values.values == [2, 4, 6]
    assert td.domain(2) == Domain.not_null()


def test_domain_translator_or_and_residual():
    res = extract_domains([
        _call("or", _call("eq", _f(0), _c(1)), _call("eq", _f(0), _c(5))),
        _call("eq", _f(1), _f(2)),  # untranslatable -> residual
    ])
    assert res.tuple_domain.domain(0).values.values == [1, 5]
    assert len(res.residuals) == 1


def test_domain_translator_neq_and_lut():
    res = extract_domains([_call("neq", _f(0), _c(7))])
    d = res.tuple_domain.domain(0)
    assert d.includes_value(6) and not d.includes_value(7) and not d.includes_value(None)
    # lut over dictionary ids
    vt = VarcharType.of(10)
    table = np.array([False, True, True, False])
    res2 = extract_domains([ir.Call("lut", (ir.FieldRef(3, vt), ir.Constant(table, vt)),
                                    vt)])
    d2 = res2.tuple_domain.domain(3)
    assert d2.values.values == [1, 2]


def test_contradiction_prunes_everything():
    res = extract_domains([_call("gt", _f(0), _c(10)), _call("lt", _f(0), _c(5))])
    assert res.tuple_domain.is_none


def test_static_split_pruning_tpch():
    """WHERE over a monotone key must skip disjoint splits entirely."""
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector

    conn = TpchConnector(sf=0.01, split_rows=1 << 10)
    calls = []
    orig = conn.generate

    def counting(split, columns=None):
        calls.append(split)
        return orig(split, columns)

    conn.generate = counting
    e = Engine()
    e.register_catalog("tpch", conn)
    s = e.create_session("tpch")
    nsplits = len(conn.splits("orders"))
    assert nsplits > 2
    r = e.execute_sql("select count(*) from orders where o_orderkey <= 100", s).rows()
    assert r[0][0] == 100
    assert len(calls) < nsplits  # pruned


def test_null_admitting_domains_never_prune(tmp_path):
    """IS NULL / OR IS NULL predicates must not skip splits via min/max stats —
    stats carry no null information (regression: null-admitting Domain pruned
    row groups and dropped every NULL row)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from trino_tpu import Engine
    from trino_tpu.connectors.parquet import ParquetConnector

    n = 5000
    vals = [None if i % 11 == 0 else i % 200 for i in range(n)]
    pq.write_table(pa.table({"val": pa.array(vals, pa.int64())}),
                   str(tmp_path / "events.parquet"), row_group_size=500)
    e = Engine()
    e.register_catalog("pq", ParquetConnector(str(tmp_path)))
    s = e.create_session("pq")
    expect_null = sum(1 for v in vals if v is None)
    expect_or = sum(1 for v in vals if v is None or v > 100)
    r1 = e.execute_sql("select count(*) from events where val is null", s).rows()
    assert r1[0][0] == expect_null
    r2 = e.execute_sql("select count(*) from events where val > 100 or val is null",
                       s).rows()
    assert r2[0][0] == expect_or
    # and non-null range predicates still prune correctly
    r3 = e.execute_sql("select count(*) from events where val = 150", s).rows()
    assert r3[0][0] == sum(1 for v in vals if v == 150)


def test_direct_groupby_late_null_page(tmp_path):
    """Direct-indexed group-by frozen from a null-free first page must fall back
    (not merge NULLs into a real group, and not crash in the recoverable
    fallback — round-1 ADVICE high finding) when a later page introduces NULL
    keys.  A dictionary-backed string key makes _key_ranges non-None, so the
    direct path is actually taken (an int64 parquet column has no dictionary
    and no column_range, so it would silently run plain hash mode)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from trino_tpu import Engine
    from trino_tpu.connectors.parquet import ParquetConnector

    ks = [["a", "b", "c"][i % 3] for i in range(900)] + \
         [None if i % 5 == 0 else ["a", "b", "c"][i % 3] for i in range(900)]
    pq.write_table(pa.table({"k": pa.array(ks, pa.string())}),
                   str(tmp_path / "t.parquet"), row_group_size=900)
    e = Engine()
    e.register_catalog("pq", ParquetConnector(str(tmp_path)))
    s = e.create_session("pq")
    rows = e.execute_sql("select k, count(*) c from t group by k order by k", s).rows()
    import collections

    expect = collections.Counter(ks)
    got = {k: c for k, c in rows}
    assert got == dict(expect)
