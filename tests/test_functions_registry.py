"""Function registry SPI + JSON functions (reference:
metadata/SystemFunctionBundle.java:384 declarative catalog;
operator/scalar/json/ + the jsonpath/ engine).

JSON documents are dictionary-encoded varchar, so each path evaluates once per
distinct document on the host and becomes a device-side id -> result gather."""

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.sql.frontend import SemanticError
from trino_tpu.sql.functions import (REGISTRY, eval_json_path, lookup,
                                     parse_json_path)


@pytest.fixture()
def json_engine():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table ev (id bigint, doc varchar)", s)
    e.execute_sql("""insert into ev values
      (1, '{"user": {"name": "ada", "age": 36}, "tags": [1,2,3]}'),
      (2, '{"user": {"name": "bob"}, "tags": []}'),
      (3, 'not json'),
      (4, '{"user": {"name": "ada", "age": 36}, "tags": [1,2,3]}')""", s)
    return e, s


def test_json_path_parser():
    assert parse_json_path("$.a.b[2]") == ["a", "b", 2]
    assert parse_json_path('$["odd key"].x') == ["odd key", "x"]
    with pytest.raises(ValueError):
        parse_json_path("a.b")
    doc = '{"a": {"b": [10, 20]}}'
    assert eval_json_path(doc, ["a", "b", 1]) == 20
    assert eval_json_path(doc, ["a", "missing"]) is None
    assert eval_json_path("not json", ["a"]) is None


def test_json_extract_scalar(json_engine):
    e, s = json_engine
    rows = e.execute_sql(
        "select id, json_extract_scalar(doc, '$.user.name') n "
        "from ev order by id", s).rows()
    assert rows == [(1, "ada"), (2, "bob"), (3, None), (4, "ada")]
    # numbers stringify; missing members and structures are NULL
    rows = e.execute_sql(
        "select id, json_extract_scalar(doc, '$.user.age') a "
        "from ev order by id", s).rows()
    assert rows == [(1, "36"), (2, None), (3, None), (4, "36")]
    rows = e.execute_sql(
        "select json_extract_scalar(doc, '$.user') u from ev where id = 1",
        s).rows()
    assert rows == [(None,)]  # structure -> NULL for the scalar form


def test_json_extract_and_lengths(json_engine):
    e, s = json_engine
    rows = e.execute_sql(
        "select json_extract(doc, '$.user') u from ev where id = 1", s).rows()
    assert rows == [('{"name":"ada","age":36}',)]
    rows = e.execute_sql(
        "select id, json_array_length(doc, '$.tags') l from ev order by id",
        s).rows()
    assert rows == [(1, 3), (2, 0), (3, None), (4, 3)]
    rows = e.execute_sql(
        "select id, json_size(doc, '$.user') z from ev order by id", s).rows()
    assert rows == [(1, 2), (2, 1), (3, None), (4, 2)]


def test_json_in_predicates_and_groupby(json_engine):
    """Extracted values behave as first-class columns (filter, group by)."""
    e, s = json_engine
    rows = e.execute_sql(
        "select count(*) c from ev "
        "where json_extract_scalar(doc, '$.user.name') = 'ada'", s).rows()
    assert rows == [(2,)]
    rows = e.execute_sql(
        "select json_extract_scalar(doc, '$.user.name') n, count(*) c "
        "from ev group by 1 order by 1 nulls last", s).rows()
    assert rows == [("ada", 2), ("bob", 1), (None, 1)]


def test_registry_show_functions(json_engine):
    """SHOW FUNCTIONS reads the one registry: json + legacy families listed
    with category/arity metadata."""
    e, s = json_engine
    rows = e.execute_sql("show functions", s).rows()
    by_name = {r[0]: r for r in rows}
    assert by_name["json_extract_scalar"][1] == "json"
    assert by_name["json_extract_scalar"][2] == "2"
    assert by_name["sum"][1] in ("aggregate", "window")
    assert "cardinality" in by_name and "upper" in by_name
    assert len(rows) > 60


def test_migrated_families_execute(json_engine):
    """Round-3 migration: the whole scalar surface is builder-backed — the
    planner's _translate_func is registry dispatch only."""
    e, s = json_engine
    e.execute_sql("create table t (k bigint, s varchar)", s)
    e.execute_sql("insert into t values (1, 'alpha'), (2, 'beta'), "
                  "(3, 'gamma')", s)
    rows = e.execute_sql(
        "select k, left(s, 2) l, right(s, 2) r, typeof(k) tk "
        "from t order by k", s).rows()
    assert rows == [(1, "al", "ha", "bigint"), (2, "be", "ta", "bigint"),
                    (3, "ga", "ma", "bigint")]
    assert e.execute_sql("select chr(65) c", s).rows() == [("A",)]
    # numeric/date/conditional families still translate post-migration
    rows = e.execute_sql(
        "select mod(k, 2) m, coalesce(nullif(k, 2), -1) z, "
        "greatest(k, 2) g from t order by k", s).rows()
    assert rows == [(1, 1, 2), (0, -1, 2), (1, 3, 3)]


def test_show_functions_all_executable(json_engine):
    """Every scalar/json/collection entry SHOW FUNCTIONS lists is executable:
    builder-backed, or one of the structural forms with dedicated syntax —
    no metadata-only facade entries (VERDICT r2 weak #5)."""
    from trino_tpu.sql.functions import REGISTRY, ensure_legacy_registered

    ensure_legacy_registered()
    structural = {"cast", "try_cast", "extract"}
    unexecutable = [n for n, f in REGISTRY.items()
                    if f.category in ("scalar", "json") and f.builder is None
                    and n not in structural]
    assert unexecutable == []


def test_registry_arity_validation(json_engine):
    e, s = json_engine
    with pytest.raises(SemanticError, match="expects 2 arguments"):
        e.execute_sql("select json_extract_scalar(doc) from ev", s)
    assert lookup("json_extract").arity == (2, 2)
    assert "json_size" in REGISTRY
