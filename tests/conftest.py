"""Test configuration: force an 8-device virtual CPU mesh (SURVEY.md §4 pattern —
multi-"node" behavior tested in one process, like the reference's DistributedQueryRunner
boots coordinator+workers in one JVM, testing/trino-testing/DistributedQueryRunner.java:108).
"""

import os
import tempfile

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# session-private XLA compilation cache: the shared persistent dir has twice
# segfaulted jax's cache READER (concurrent suite runs / timeout-killed
# processes leaving entries another process then loads).  A fresh dir per
# pytest session keeps the cross-PROCESS sharing the cluster/worker tests
# rely on while making stale-entry corruption impossible.
if "JAX_COMPILATION_CACHE_DIR" not in os.environ:
    import atexit
    import shutil

    _cache_tmp = tempfile.mkdtemp(prefix="trino_tpu_testcache_")
    os.environ["JAX_COMPILATION_CACHE_DIR"] = _cache_tmp
    atexit.register(shutil.rmtree, _cache_tmp, True)
# JAX_PLATFORMS=cpu as an ENV VAR hangs the axon plugin's discovery at the
# first device use; drop it and select cpu via jax.config below (which works)
os.environ.pop("JAX_PLATFORMS", None)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_between_modules():
    """XLA:CPU has segfaulted compiling window kernels late in the full suite
    (observed at tests #333/#340 across runs; the same tests pass standalone)
    — accumulated compiled-executable state in one long-lived process is the
    only difference.  Dropping jax's in-process caches between modules keeps
    the process footprint flat; module-internal reuse (the expensive part) is
    unaffected."""
    yield
    jax.clear_caches()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process integration tests (subprocess workers)")


# Modules that dominate suite wall-clock on the 1-2 core build box: the
# 8-device-mesh distributed/FTE/cluster integration families (minutes of real
# SPMD work each since the jax-0.4.x shard_map shim made them run again) and
# the SF1 budget module (~100s of XLA compiles).  Scheduled LAST, cheapest
# first, so the driver's wall-clock-capped tier-1 run spends its budget on
# broad coverage before the expensive integration tail.
_HEAVY_TAIL = ("test_query_budgets", "test_fte", "test_cluster",
               "test_distributed")


def pytest_collection_modifyitems(config, items):
    def tail_rank(item):
        name = item.fspath.basename
        for i, prefix in enumerate(_HEAVY_TAIL):
            if name.startswith(prefix):
                return i + 1
        return 0

    items.sort(key=tail_rank)  # stable: in-module order is untouched


@pytest.fixture(scope="session")
def tpch_sf001():
    from trino_tpu.connectors.tpch import TpchConnector

    return TpchConnector(sf=0.01)


@pytest.fixture(scope="session")
def engine(tpch_sf001):
    from trino_tpu import Engine

    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    return e


@pytest.fixture(scope="session")
def tpch_pandas(tpch_sf001):
    """Host-side oracle: full TPC-H tables as pandas DataFrames (decoded)."""
    import numpy as np
    import pandas as pd

    tables = {}
    for t in tpch_sf001.tables():
        frames = []
        for split in tpch_sf001.splits(t):
            page = tpch_sf001.generate(split)
            frames.append(pd.DataFrame(page.to_numpy(tpch_sf001.dictionaries(t))))
        tables[t] = pd.concat(frames, ignore_index=True)
    return tables
