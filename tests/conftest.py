"""Test configuration: force an 8-device virtual CPU mesh (SURVEY.md §4 pattern —
multi-"node" behavior tested in one process, like the reference's DistributedQueryRunner
boots coordinator+workers in one JVM, testing/trino-testing/DistributedQueryRunner.java:108).
"""

import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
# JAX_PLATFORMS=cpu as an ENV VAR hangs the axon plugin's discovery at the
# first device use; drop it and select cpu via jax.config below (which works)
os.environ.pop("JAX_PLATFORMS", None)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: multi-process integration tests (subprocess workers)")


@pytest.fixture(scope="session")
def tpch_sf001():
    from trino_tpu.connectors.tpch import TpchConnector

    return TpchConnector(sf=0.01)


@pytest.fixture(scope="session")
def engine(tpch_sf001):
    from trino_tpu import Engine

    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    return e


@pytest.fixture(scope="session")
def tpch_pandas(tpch_sf001):
    """Host-side oracle: full TPC-H tables as pandas DataFrames (decoded)."""
    import numpy as np
    import pandas as pd

    tables = {}
    for t in tpch_sf001.tables():
        frames = []
        for split in tpch_sf001.splits(t):
            page = tpch_sf001.generate(split)
            frames.append(pd.DataFrame(page.to_numpy(tpch_sf001.dictionaries(t))))
        tables[t] = pd.concat(frames, ignore_index=True)
    return tables
