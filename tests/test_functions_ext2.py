"""Second extended function batch: digests/codecs, HMAC, statistical CDFs,
JSON parse/format, ISO-8601 breadth, soundex/luhn/concat_ws/from_base
(reference: operator/scalar/VarbinaryFunctions, MathFunctions, JsonFunctions,
DateTimeFunctions test models)."""

import base64
import hashlib
import hmac
import math
import zlib

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector


@pytest.fixture(scope="module")
def feng():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table t (x double, n bigint, s varchar, j varchar)", s)
    e.execute_sql("""insert into t values
        (0.25, 1, 'hello', '[1, 2, 3]'),
        (0.5,  2, 'world', '{"a": {"b": 7}}'),
        (0.75, 3, 'MTIzNDU2', '"scalar"'),
        (0.9,  4, '79927398713', 'not json')""", s)
    return e, s


def _col(feng, expr, order="n"):
    e, s = feng
    r = e.execute_sql(f"select {expr} v from t order by {order}", s).to_pandas()
    return list(r["v"])


def _one(feng, expr, where="n = 1"):
    e, s = feng
    r = e.execute_sql(f"select {expr} v from t where {where}", s).to_pandas()
    return r.iloc[0, 0]


def test_digests(feng):
    assert _one(feng, "sha1(s)") == hashlib.sha1(b"hello").hexdigest()
    assert _one(feng, "sha512(s)") == hashlib.sha512(b"hello").hexdigest()
    assert _one(feng, "crc32(s)") == zlib.crc32(b"hello") & 0xFFFFFFFF
    # xxhash64 of 'hello' (public XXH64 vector, seed 0)
    assert _one(feng, "xxhash64(s)") == 0x26C7827D889F6DA3


def test_hmac(feng):
    for algo in ("md5", "sha1", "sha256", "sha512"):
        want = hmac.new(b"key", b"hello", algo).hexdigest()
        assert _one(feng, f"hmac_{algo}(s, 'key')") == want


def test_base64(feng):
    assert _one(feng, "to_base64(s)") == base64.b64encode(b"hello").decode()
    assert _one(feng, "from_base64(s)", "n = 3") == "123456"
    assert _one(feng, "to_base64url(s)") == \
        base64.urlsafe_b64encode(b"hello").decode()
    assert _one(feng, "from_base64url(s)", "n = 3") == "123456"


def test_from_base(feng):
    assert _one(feng, "from_base('ff', 16)") == 255
    assert _one(feng, "from_base('101', 2)") == 5
    assert _one(feng, "from_base(s, 16)") is None  # 'hello' is not hex


def test_soundex_luhn(feng):
    assert _one(feng, "soundex('Robert')") == "R163"
    assert _one(feng, "soundex(s)", "n = 2") == "W643"  # world
    assert bool(_one(feng, "luhn_check(s)", "n = 4"))
    assert _one(feng, "luhn_check(s)", "n = 1") is None  # not digits


def test_concat_ws(feng):
    assert _one(feng, "concat_ws('-', s, 'x')") == "hello-x"
    assert _one(feng, "concat_ws(', ', 'a', 'b', 'c')") == "a, b, c"


def test_json_family(feng):
    assert _one(feng, "json_parse(j)") == "[1,2,3]"
    assert _one(feng, "json_parse(j)", "n = 4") is None
    assert _one(feng, "json_format(j)", "n = 2") == '{"a":{"b":7}}'
    assert bool(_one(feng, "is_json_scalar(j)", "n = 3"))
    assert not bool(_one(feng, "is_json_scalar(j)", "n = 1"))
    assert bool(_one(feng, "json_array_contains(j, 2)"))
    assert not bool(_one(feng, "json_array_contains(j, 9)"))
    assert _one(feng, "json_array_get(j, 1)") == "2"
    assert _one(feng, "json_array_get(j, -1)") == "3"
    assert _one(feng, "json_array_get(j, 7)") is None


def test_iso8601(feng):
    assert _one(feng, "to_iso8601(date '2024-02-29')") == "2024-02-29"
    got = _one(feng, "from_iso8601_timestamp('2024-02-29T12:30:45')")
    assert str(got).startswith("2024-02-29 12:30:45")


def test_cdfs(feng):
    assert abs(_one(feng, "normal_cdf(0, 1, 0)") - 0.5) < 1e-12
    assert abs(_one(feng, "normal_cdf(0, 1, 1.96)") - 0.9750021) < 1e-6
    assert abs(_one(feng, "inverse_normal_cdf(0, 1, 0.975)") - 1.959964) < 1e-5
    assert abs(_one(feng, "beta_cdf(2, 2, 0.5)") - 0.5) < 1e-9
    lo = _one(feng, "wilson_interval_lower(20, 100, 1.96)")
    hi = _one(feng, "wilson_interval_upper(20, 100, 1.96)")
    # known Wilson bounds for 20/100 at z=1.96
    assert abs(lo - 0.1333) < 5e-4, lo
    assert abs(hi - 0.2888) < 5e-4, hi
    assert lo < 0.2 < hi


def test_cdf_on_column(feng):
    got = _col(feng, "normal_cdf(0, 1, x)")
    want = [0.5 * (1 + math.erf(v / math.sqrt(2)))
            for v in (0.25, 0.5, 0.75, 0.9)]
    for g, w in zip(got, want):
        assert abs(g - w) < 1e-12


def test_now(feng):
    got = _one(feng, "now()")
    assert str(got).startswith("20")


def test_split_family(feng):
    e, s = feng
    e.execute_sql("create table sp (s varchar, n bigint)", s)
    e.execute_sql("insert into sp values ('a,b,c', 1), ('x', 2), "
                  "('k1=v1;k2=v2', 3)", s)
    r = e.execute_sql("select n, split(s, ',') v from sp order by n",
                      s).to_pandas()
    assert list(r["v"].iloc[0]) == ["a", "b", "c"]
    assert list(r["v"].iloc[1]) == ["x"]
    r = e.execute_sql("select split(s, ',')[2] v, "
                      "cardinality(split(s, ',')) c from sp where n = 1",
                      s).to_pandas()
    assert r["v"].iloc[0] == "b" and r["c"].iloc[0] == 3
    r = e.execute_sql("select split('a,b,c,d', ',', 2) v from sp where n = 1",
                      s).to_pandas()
    assert list(r["v"].iloc[0]) == ["a", "b,c,d"]
    r = e.execute_sql("select split_to_map(s, ';', '=') m from sp where n = 3",
                      s).to_pandas()
    assert r["m"].iloc[0] == {"k1": "v1", "k2": "v2"}


def test_datetime_batch3(feng):
    got = _one(feng, "parse_datetime('2024-02-29 12:30', 'yyyy-MM-dd HH:mm')")
    assert str(got).startswith("2024-02-29 12:30")
    assert _one(feng, "parse_datetime('junk', 'yyyy-MM-dd')") is None
    assert _one(feng, "current_timezone()") == "UTC"
    assert _one(feng, "timezone_hour(now())") == 0
    assert _one(feng, "timezone_minute(now())") == 0
    assert str(_one(feng, "version()")).startswith("trino-tpu")


def test_base32(feng):
    assert _one(feng, "from_base32(to_base32('hello'))") == "hello"
    assert _one(feng, "to_base32(s)") is not None
