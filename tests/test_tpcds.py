"""TPC-DS connector + canonical store-sales star queries vs a pandas oracle.

Reference: plugin/trino-tpcds + testing/trino-benchto-benchmarks tpcds suite;
correctness checked the way the engine suites use H2 (pandas here) as oracle.
"""

import numpy as np
import pandas as pd
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpcds import TpcdsConnector

SF = 0.01


@pytest.fixture(scope="module")
def eng():
    e = Engine()
    e.register_catalog("tpcds", TpcdsConnector(sf=SF, split_rows=1 << 14))
    return e, e.create_session("tpcds")


@pytest.fixture(scope="module")
def host(eng):
    e, _ = eng
    conn = e.catalogs["tpcds"]
    out = {}
    for t in ("store_sales", "date_dim", "item", "promotion",
              "customer_demographics", "customer", "customer_address"):
        schema = conn.schema(t)
        dicts = conn.dictionaries(t)
        cols = {}
        for f in schema.fields:
            parts = []
            for sp in conn.splits(t):
                pg = conn.generate(sp, [f.name])
                a = np.asarray(pg.column(f.name))
                if pg.valid is not None:  # uniform splits mask the overshoot
                    a = a[np.asarray(pg.valid_mask())]
                parts.append(a)
            arr = np.concatenate(parts)
            d = dicts.get(f.name)
            if d is not None:
                arr = d.decode(arr)
            cols[f.name] = arr
        out[t] = pd.DataFrame(cols)
    return out


def test_generators_cover_schemas(eng):
    e, _ = eng
    conn = e.catalogs["tpcds"]
    from trino_tpu.connectors.tpcds import GENERATORS, SCHEMAS

    for t, schema in SCHEMAS.items():
        cols = GENERATORS[t](SF, 0, 4)
        assert set(cols) == set(schema.names), t


def test_row_counts(eng):
    e, s = eng
    r = e.execute_sql("select count(*) from store_sales", s).rows()
    assert r[0][0] == int(2_880_000 * SF)
    assert e.execute_sql("select count(*) from customer_demographics", s
                         ).rows()[0][0] == 1_920_800
    assert e.execute_sql("select count(*) from date_dim", s).rows()[0][0] == 4748


def test_q42_category_report(eng, host):
    e, s = eng
    got = e.execute_sql("""
        select d_year, i_category_id, i_category, sum(ss_ext_sales_price) total
        from date_dim, store_sales, item
        where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
          and i_manager_id = 1 and d_moy = 11 and d_year = 2000
        group by d_year, i_category_id, i_category
        order by total desc, d_year, i_category_id limit 100""", s).rows()
    ss, dd, it = host["store_sales"], host["date_dim"], host["item"]
    j = ss.merge(dd[(dd.d_moy == 11) & (dd.d_year == 2000)][["d_date_sk", "d_year"]],
                 left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(it[it.i_manager_id == 1][["i_item_sk", "i_category_id",
                                          "i_category"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    exp = (j.assign(v=j.ss_ext_sales_price / 100.0)
           .groupby(["d_year", "i_category_id", "i_category"])["v"].sum()
           .reset_index().sort_values(["v", "d_year", "i_category_id"],
                                      ascending=[False, True, True]).head(100))
    assert len(got) == len(exp)
    for row, (_, er) in zip(got, exp.iterrows()):
        assert row[0] == er.d_year and row[1] == er.i_category_id \
            and row[2] == er.i_category
        assert abs(float(row[3]) - er.v) < 1e-6


def test_q55_brand_revenue(eng, host):
    e, s = eng
    got = e.execute_sql("""
        select i_brand_id, i_brand, sum(ss_ext_sales_price) ext_price
        from date_dim, store_sales, item
        where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
          and i_manager_id = 28 and d_moy = 11 and d_year = 1999
        group by i_brand_id, i_brand
        order by ext_price desc, i_brand_id limit 100""", s).rows()
    ss, dd, it = host["store_sales"], host["date_dim"], host["item"]
    j = ss.merge(dd[(dd.d_moy == 11) & (dd.d_year == 1999)][["d_date_sk"]],
                 left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(it[it.i_manager_id == 28][["i_item_sk", "i_brand_id", "i_brand"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    exp = (j.assign(v=j.ss_ext_sales_price / 100.0)
           .groupby(["i_brand_id", "i_brand"])["v"].sum().reset_index()
           .sort_values(["v", "i_brand_id"], ascending=[False, True]).head(100))
    assert len(got) == len(exp)
    for row, (_, er) in zip(got, exp.iterrows()):
        assert row[0] == er.i_brand_id and row[1] == er.i_brand
        assert abs(float(row[2]) - er.v) < 1e-6


def test_q3_brand_by_year(eng, host):
    e, s = eng
    got = e.execute_sql("""
        select d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) sum_agg
        from date_dim, store_sales, item
        where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
          and i_manufact_id = 28 and d_moy = 11
        group by d_year, i_brand_id, i_brand
        order by d_year, sum_agg desc, i_brand_id limit 100""", s).rows()
    ss, dd, it = host["store_sales"], host["date_dim"], host["item"]
    j = ss.merge(dd[dd.d_moy == 11][["d_date_sk", "d_year"]],
                 left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(it[it.i_manufact_id == 28][["i_item_sk", "i_brand_id", "i_brand"]],
                left_on="ss_item_sk", right_on="i_item_sk")
    exp = (j.assign(v=j.ss_ext_sales_price / 100.0)
           .groupby(["d_year", "i_brand_id", "i_brand"])["v"].sum().reset_index()
           .sort_values(["d_year", "v", "i_brand_id"],
                        ascending=[True, False, True]).head(100))
    assert len(got) == len(exp)
    for row, (_, er) in zip(got, exp.iterrows()):
        assert row[0] == er.d_year and row[1] == er.i_brand_id
        assert abs(float(row[3]) - er.v) < 1e-6


def test_q7_demographic_averages(eng, host):
    e, s = eng
    got = e.execute_sql("""
        select i_item_id, avg(ss_quantity) agg1, avg(ss_list_price) agg2,
               avg(ss_coupon_amt) agg3, avg(ss_sales_price) agg4
        from store_sales, customer_demographics, date_dim, item, promotion
        where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
          and ss_cdemo_sk = cd_demo_sk and ss_promo_sk = p_promo_sk
          and cd_gender = 'M' and cd_marital_status = 'S'
          and cd_education_status = 'College'
          and (p_channel_email = 'N' or p_channel_event = 'N')
          and d_year = 2000
        group by i_item_id order by i_item_id limit 100""", s).rows()
    ss, dd, it = host["store_sales"], host["date_dim"], host["item"]
    cd, pr = host["customer_demographics"], host["promotion"]
    j = ss.merge(dd[dd.d_year == 2000][["d_date_sk"]],
                 left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(it[["i_item_sk", "i_item_id"]], left_on="ss_item_sk",
                right_on="i_item_sk")
    cdf = cd[(cd.cd_gender == "M") & (cd.cd_marital_status == "S")
             & (cd.cd_education_status == "College")][["cd_demo_sk"]]
    j = j.merge(cdf, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
    prf = pr[(pr.p_channel_email == "N") | (pr.p_channel_event == "N")][["p_promo_sk"]]
    j = j.merge(prf, left_on="ss_promo_sk", right_on="p_promo_sk")
    exp = (j.groupby("i_item_id")
           .agg(agg1=("ss_quantity", "mean"), agg2=("ss_list_price", "mean"),
                agg3=("ss_coupon_amt", "mean"), agg4=("ss_sales_price", "mean"))
           .reset_index().sort_values("i_item_id").head(100))
    assert len(got) == len(exp)
    for row, (_, er) in zip(got, exp.iterrows()):
        assert row[0] == er.i_item_id
        assert abs(float(row[1]) - er.agg1) < 1e-9  # int avg: exact double
        # decimal averages round half-up at scale 2
        for gi, ev in ((2, er.agg2), (3, er.agg3), (4, er.agg4)):
            assert abs(float(row[gi]) - ev / 100.0) <= 0.005 + 1e-9


def test_split_pruning_on_date_dim(eng):
    e, s = eng
    conn = e.catalogs["tpcds"]
    r = e.execute_sql(
        "select count(*) from date_dim where d_date_sk < 2450100", s).rows()
    assert r[0][0] == 100


def test_q89_monthly_category_window(eng, host):
    """Q89 shape: per (category, brand, month) sales vs the category's average
    monthly sales via a window AVG — exercises windows over the DS star
    (reference: tpcds q89)."""
    e, s = eng
    got = e.execute_sql("""
        select i_category, i_brand, d_moy, sum_sales, avg_monthly_sales
        from (
          select i_category, i_brand, d_moy,
                 sum(ss_sales_price) as sum_sales,
                 -- cast: decimal avg rounds half-up to the input scale
                 -- (Trino semantics); the float oracle needs double math
                 avg(cast(sum(ss_sales_price) as double))
                     over (partition by i_category, i_brand)
                     as avg_monthly_sales
          from store_sales, item, date_dim
          where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
            and d_year = 2000 and i_category = 'Books'
          group by i_category, i_brand, d_moy
        ) x
        where avg_monthly_sales > 0
          and abs(sum_sales - avg_monthly_sales) / avg_monthly_sales > 0.1
        order by i_brand, d_moy limit 50""", s).to_pandas()
    ss, it, dd = host["store_sales"], host["item"], host["date_dim"]
    j = ss.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j = j.merge(dd[dd.d_year == 2000], left_on="ss_sold_date_sk",
                right_on="d_date_sk")
    j = j[j.i_category == "Books"]
    g = (j.groupby(["i_category", "i_brand", "d_moy"])
         .ss_sales_price.sum().div(100).reset_index(name="sum_sales"))
    g["avg_monthly_sales"] = g.groupby(["i_category", "i_brand"])[
        "sum_sales"].transform("mean")
    g = g[(g.avg_monthly_sales > 0)
          & ((g.sum_sales - g.avg_monthly_sales).abs()
             / g.avg_monthly_sales > 0.1)]
    exp = g.sort_values(["i_brand", "d_moy"]).head(50).reset_index(drop=True)
    assert len(got) == len(exp)
    np.testing.assert_allclose(got["sum_sales"].to_numpy(),
                               exp["sum_sales"].to_numpy(), rtol=1e-9)
    np.testing.assert_allclose(got["avg_monthly_sales"].to_numpy(),
                               exp["avg_monthly_sales"].to_numpy(), rtol=1e-9)


def test_q98_class_revenue_ratio(eng, host):
    """Q98 shape: per-item revenue share of its class via a window SUM
    (reference: tpcds q98)."""
    e, s = eng
    got = e.execute_sql("""
        select i_item_id, i_class, revenue,
               revenue * 100.0 / sum(revenue) over (partition by i_class)
                   as revenueratio
        from (
          select i_item_id, i_class, sum(ss_ext_sales_price) as revenue
          from store_sales, item, date_dim
          where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk
            and i_category = 'Music' and d_year = 2001 and d_moy = 5
          group by i_item_id, i_class
        ) x order by i_class, i_item_id""", s).to_pandas()
    ss, it, dd = host["store_sales"], host["item"], host["date_dim"]
    j = ss.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j = j.merge(dd[(dd.d_year == 2001) & (dd.d_moy == 5)],
                left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j[j.i_category == "Music"]
    g = (j.groupby(["i_item_id", "i_class"]).ss_ext_sales_price.sum().div(100)
         .reset_index(name="revenue"))
    g["revenueratio"] = (g.revenue * 100.0
                         / g.groupby("i_class").revenue.transform("sum"))
    g = g.sort_values(["i_class", "i_item_id"]).reset_index(drop=True)
    assert len(got) == len(g)
    np.testing.assert_allclose(got["revenue"].to_numpy(),
                               g["revenue"].to_numpy(), rtol=1e-9)
    np.testing.assert_allclose(got["revenueratio"].to_numpy(),
                               g["revenueratio"].to_numpy(), rtol=1e-9)


def test_q6_state_price_comparison(eng, host):
    """Q6 shape: customers' states whose purchased items cost >= 1.2x the
    category average — correlated scalar-aggregate subquery over the star
    (reference: tpcds q6)."""
    e, s = eng
    got = e.execute_sql("""
        select ca_state, count(*) cnt
        from customer_address, customer, store_sales, item
        where ca_address_sk = c_current_addr_sk
          and c_customer_sk = ss_customer_sk
          and ss_item_sk = i_item_sk
          and i_current_price / 1.2 > (
              select avg(j.i_current_price) from item j
              where j.i_category = item.i_category)
        group by ca_state having count(*) >= 10
        order by cnt, ca_state limit 10""", s).to_pandas()
    ss, it = host["store_sales"], host["item"]
    ca, cu = host["customer_address"], host["customer"]
    cat_avg = it.groupby("i_category").i_current_price.mean()
    it2 = it[it.i_current_price > 1.2 * it.i_category.map(cat_avg)]
    j = ss.merge(it2, left_on="ss_item_sk", right_on="i_item_sk")
    j = j.merge(cu, left_on="ss_customer_sk", right_on="c_customer_sk")
    j = j.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
    g = j.groupby("ca_state").size().reset_index(name="cnt")
    g = g[g.cnt >= 10].sort_values(["cnt", "ca_state"]).head(10)
    assert got["cnt"].tolist() == g["cnt"].tolist()
    assert got["ca_state"].tolist() == g["ca_state"].tolist()


# --------------------------------------------------------- round 3: channels
@pytest.fixture(scope="module")
def host2(eng):
    """Host copies of the new channel tables (projected columns only)."""
    e, _ = eng
    conn = e.catalogs["tpcds"]
    wanted = {
        "catalog_sales": ["cs_sold_date_sk", "cs_item_sk", "cs_ext_sales_price",
                          "cs_call_center_sk", "cs_quantity"],
        "web_sales": ["ws_sold_date_sk", "ws_item_sk", "ws_ext_sales_price",
                      "ws_web_site_sk"],
        "store_returns": ["sr_item_sk", "sr_return_amt", "sr_reason_sk"],
        "inventory": ["inv_date_sk", "inv_item_sk", "inv_warehouse_sk",
                      "inv_quantity_on_hand"],
        "date_dim": ["d_date_sk", "d_year", "d_moy"],
        "item": ["i_item_sk", "i_category"],
        "warehouse": ["w_warehouse_sk", "w_warehouse_name"],
    }
    out = {}
    for t, names in wanted.items():
        dicts = conn.dictionaries(t)
        cols = {}
        for name in names:
            parts = []
            for sp in conn.splits(t):
                pg = conn.generate(sp, [name])
                a = np.asarray(pg.column(name))
                if pg.valid is not None:  # uniform splits mask the overshoot
                    a = a[np.asarray(pg.valid_mask())]
                parts.append(a)
            arr = np.concatenate(parts)
            if dicts.get(name) is not None:
                arr = dicts[name].decode(arr)
            cols[name] = arr
        out[t] = pd.DataFrame(cols)
    return out


def test_catalog_channel_by_year(eng, host2):
    """Catalog-channel revenue by year (the Q20/Q26-family shape over
    catalog_sales ⋈ date_dim)."""
    e, s = eng
    got = e.execute_sql(
        "select d_year, sum(cs_ext_sales_price) rev, count(*) c "
        "from catalog_sales, date_dim where cs_sold_date_sk = d_date_sk "
        "and d_year between 1998 and 2000 group by d_year order by d_year",
        s).to_pandas()
    cs, dd = host2["catalog_sales"], host2["date_dim"]
    j = cs.merge(dd, left_on="cs_sold_date_sk", right_on="d_date_sk")
    j = j[(j.d_year >= 1998) & (j.d_year <= 2000)]
    ref = j.groupby("d_year").agg(
        rev=("cs_ext_sales_price", "sum"), c=("d_year", "size")).reset_index()
    assert got.d_year.tolist() == sorted(ref.d_year.tolist())
    np.testing.assert_allclose(got.rev.to_numpy(),
                               ref.sort_values("d_year").rev.to_numpy() / 100,
                               rtol=1e-9)


def test_cross_channel_union(eng, host2):
    """Store+catalog+web revenue per item category (the Q33/Q56 cross-channel
    UNION shape)."""
    e, s = eng
    got = e.execute_sql("""
        select i_category, sum(rev) total from (
          select ws_item_sk item_sk, sum(ws_ext_sales_price) rev
          from web_sales group by ws_item_sk
          union all
          select cs_item_sk, sum(cs_ext_sales_price) from catalog_sales
          group by cs_item_sk
        ) u, item where u.item_sk = i_item_sk
        group by i_category order by i_category""", s).to_pandas()
    ws, cs, it = host2["web_sales"], host2["catalog_sales"], host2["item"]
    w = ws.groupby("ws_item_sk").ws_ext_sales_price.sum().rename("rev")
    c = cs.groupby("cs_item_sk").cs_ext_sales_price.sum().rename("rev")
    u = pd.concat([w.reset_index().rename(columns={"ws_item_sk": "k"}),
                   c.reset_index().rename(columns={"cs_item_sk": "k"})])
    j = u.merge(it, left_on="k", right_on="i_item_sk")
    ref = j.groupby("i_category").rev.sum().reset_index().sort_values(
        "i_category")
    assert got.i_category.tolist() == ref.i_category.tolist()
    np.testing.assert_allclose(got.total.to_numpy(),
                               ref.rev.to_numpy() / 100, rtol=1e-9)


def test_q22_inventory_by_warehouse(eng, host2):
    """Average quantity on hand per warehouse (the Q22 inventory rollup
    shape)."""
    e, s = eng
    got = e.execute_sql(
        "select w_warehouse_name, avg(inv_quantity_on_hand) q "
        "from inventory, warehouse where inv_warehouse_sk = w_warehouse_sk "
        "group by w_warehouse_name order by w_warehouse_name", s).to_pandas()
    inv, w = host2["inventory"], host2["warehouse"]
    j = inv.merge(w, left_on="inv_warehouse_sk", right_on="w_warehouse_sk")
    ref = j.groupby("w_warehouse_name").inv_quantity_on_hand.mean() \
        .reset_index().sort_values("w_warehouse_name")
    assert got.w_warehouse_name.tolist() == ref.w_warehouse_name.tolist()
    np.testing.assert_allclose(got.q.to_numpy(),
                               ref.inv_quantity_on_hand.to_numpy(), rtol=1e-9)


def test_returns_join_reason(eng, host2):
    e, s = eng
    got = e.execute_sql(
        "select r_reason_desc, sum(sr_return_amt) amt from store_returns, "
        "reason where sr_reason_sk = r_reason_sk "
        "group by r_reason_desc order by amt desc limit 5", s).rows()
    assert len(got) == 5
    sr = host2["store_returns"]
    ref = sr.groupby("sr_reason_sk").sr_return_amt.sum().sort_values(
        ascending=False)
    np.testing.assert_allclose(
        [r[1] for r in got], (ref.head(5) / 100).to_numpy(), rtol=1e-9)


@pytest.fixture(scope="module")
def host3(eng):
    """Host copies for the Q19/Q65 store-geography shapes."""
    e, _ = eng
    conn = e.catalogs["tpcds"]
    wanted = {
        "store_sales": ["ss_sold_date_sk", "ss_item_sk", "ss_customer_sk",
                        "ss_store_sk", "ss_ext_sales_price", "ss_sales_price"],
        "date_dim": ["d_date_sk", "d_year", "d_moy"],
        "item": ["i_item_sk", "i_brand_id", "i_brand", "i_manager_id",
                 "i_item_desc"],
        "customer": ["c_customer_sk", "c_current_addr_sk"],
        "customer_address": ["ca_address_sk"],
        "store": ["s_store_sk", "s_store_name"],
    }
    out = {}
    for t, names in wanted.items():
        dicts = conn.dictionaries(t)
        cols = {}
        for name in names:
            parts = []
            for sp in conn.splits(t):
                pg = conn.generate(sp, [name])
                a = np.asarray(pg.column(name))
                if pg.valid is not None:  # uniform splits mask the overshoot
                    a = a[np.asarray(pg.valid_mask())]
                parts.append(a)
            arr = np.concatenate(parts)
            if dicts.get(name) is not None:
                arr = dicts[name].decode(arr)
            cols[name] = arr
        out[t] = pd.DataFrame(cols)
    return out


def test_q19_brand_revenue_by_geography(eng, host3):
    """Q19 shape: brand ext-price for a manager's items in one month, joined
    through customer geography and store (6-table star join)."""
    e, s = eng
    got = e.execute_sql("""
        select i_brand_id, i_brand, sum(ss_ext_sales_price) ext_price
        from date_dim, store_sales, item, customer, customer_address, store
        where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk
          and i_manager_id = 8 and d_moy = 11 and d_year = 1998
          and ss_customer_sk = c_customer_sk
          and c_current_addr_sk = ca_address_sk and ss_store_sk = s_store_sk
        group by i_brand_id, i_brand
        order by ext_price desc, i_brand_id limit 10""", s).to_pandas()

    ss = host3["store_sales"]; dd = host3["date_dim"]; it = host3["item"]
    cu = host3["customer"]; ca = host3["customer_address"]; st = host3["store"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j = j[(j.i_manager_id == 8) & (j.d_moy == 11) & (j.d_year == 1998)]
    j = j.merge(cu, left_on="ss_customer_sk", right_on="c_customer_sk")
    j = j.merge(ca, left_on="c_current_addr_sk", right_on="ca_address_sk")
    j = j.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    exp = (j.assign(p=j.ss_ext_sales_price / 100.0)
           .groupby(["i_brand_id", "i_brand"])["p"].sum().reset_index()
           .sort_values(["p", "i_brand_id"], ascending=[False, True])
           .head(10))
    assert len(got) == len(exp)
    np.testing.assert_allclose(got["ext_price"].to_numpy().astype(float),
                               exp["p"].to_numpy(), rtol=1e-9)
    assert got["i_brand_id"].tolist() == exp["i_brand_id"].tolist()


def test_q65_store_item_revenue(eng, host3):
    """Q65 shape: per (store, item) revenue from a derived aggregate, joined
    back to dimensions (subquery-in-FROM + two joins)."""
    e, s = eng
    got = e.execute_sql("""
        select s_store_name, i_item_desc, sc.revenue
        from store, item,
         (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
          from store_sales group by ss_store_sk, ss_item_sk) sc
        where sc.ss_store_sk = s_store_sk and sc.ss_item_sk = i_item_sk
        order by s_store_name, revenue desc, i_item_desc limit 25""",
        s).to_pandas()

    ss = host3["store_sales"]; it = host3["item"]; st = host3["store"]
    agg = (ss.assign(p=ss.ss_sales_price / 100.0)
           .groupby(["ss_store_sk", "ss_item_sk"])["p"].sum().reset_index())
    j = agg.merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j = j.merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    exp = j.sort_values(["s_store_name", "p", "i_item_desc"],
                        ascending=[True, False, True]).head(25)
    np.testing.assert_allclose(got["revenue"].to_numpy().astype(float),
                               exp["p"].to_numpy(), rtol=1e-9)
    assert got["s_store_name"].tolist() == exp["s_store_name"].tolist()


def test_q26_catalog_demographics(eng):
    """Q26 shape: catalog-channel averages for a demographic slice with a
    promotion-channel OR predicate (5-table star)."""
    e, s = eng
    conn = e.catalogs["tpcds"]
    got = e.execute_sql("""
        select i_item_id, avg(cs_quantity) agg1, avg(cs_list_price) agg2,
               avg(cs_coupon_amt) agg3, avg(cs_sales_price) agg4
        from catalog_sales, customer_demographics, date_dim, item, promotion
        where cs_sold_date_sk = d_date_sk and cs_item_sk = i_item_sk
          and cs_bill_cdemo_sk = cd_demo_sk and cs_promo_sk = p_promo_sk
          and cd_gender = 'M' and cd_marital_status = 'S'
          and cd_education_status = 'College'
          and (p_channel_email = 'N' or p_channel_event = 'N')
          and d_year = 2000
        group by i_item_id order by i_item_id limit 10""", s).to_pandas()

    wanted = {
        "catalog_sales": ["cs_sold_date_sk", "cs_item_sk", "cs_bill_cdemo_sk",
                          "cs_promo_sk", "cs_quantity", "cs_list_price",
                          "cs_coupon_amt", "cs_sales_price"],
        "customer_demographics": ["cd_demo_sk", "cd_gender",
                                  "cd_marital_status", "cd_education_status"],
        "date_dim": ["d_date_sk", "d_year"],
        "item": ["i_item_sk", "i_item_id"],
        "promotion": ["p_promo_sk", "p_channel_email", "p_channel_event"],
    }
    T = {}
    for t, names in wanted.items():
        dicts = conn.dictionaries(t)
        cols = {}
        for name in names:
            parts = []
            for sp in conn.splits(t):
                pg = conn.generate(sp, [name])
                a = np.asarray(pg.column(name))
                if pg.valid is not None:  # uniform splits mask the overshoot
                    a = a[np.asarray(pg.valid_mask())]
                parts.append(a)
            arr = np.concatenate(parts)
            if dicts.get(name) is not None:
                arr = dicts[name].decode(arr)
            cols[name] = arr
        T[t] = pd.DataFrame(cols)

    j = T["catalog_sales"].merge(
        T["date_dim"], left_on="cs_sold_date_sk", right_on="d_date_sk")
    j = j.merge(T["item"], left_on="cs_item_sk", right_on="i_item_sk")
    j = j.merge(T["customer_demographics"], left_on="cs_bill_cdemo_sk",
                right_on="cd_demo_sk")
    j = j.merge(T["promotion"], left_on="cs_promo_sk", right_on="p_promo_sk")
    j = j[(j.cd_gender == "M") & (j.cd_marital_status == "S")
          & (j.cd_education_status == "College")
          & ((j.p_channel_email == "N") | (j.p_channel_event == "N"))
          & (j.d_year == 2000)]
    for c in ("cs_list_price", "cs_coupon_amt", "cs_sales_price"):
        j[c] = j[c] / 100.0
    exp = (j.groupby("i_item_id")
           .agg(agg1=("cs_quantity", "mean"), agg2=("cs_list_price", "mean"),
                agg3=("cs_coupon_amt", "mean"), agg4=("cs_sales_price", "mean"))
           .reset_index().sort_values("i_item_id").head(10))
    assert got["i_item_id"].tolist() == exp["i_item_id"].tolist()
    np.testing.assert_allclose(got["agg1"].to_numpy().astype(float),
                               exp["agg1"].to_numpy(), rtol=1e-9)
    # decimal avgs round HALF_UP to the input scale (cents)
    np.testing.assert_allclose(got["agg2"].to_numpy().astype(float),
                               exp["agg2"].to_numpy(), atol=0.005)
    np.testing.assert_allclose(got["agg4"].to_numpy().astype(float),
                               exp["agg4"].to_numpy(), atol=0.005)
