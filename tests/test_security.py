"""Access control SPI, rule engine, HTTP auth, metrics endpoint, web UI
(reference: spi/security SystemAccessControl + file-based access control;
JmxOpenMetricsModule; core/trino-web-ui's cluster overview)."""

import json
import urllib.request

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.spi.security import (AccessDeniedError, RuleBasedAccessControl)


@pytest.fixture()
def secured_engine(tpch_sf001):
    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    e.register_catalog("mem", MemoryConnector())
    e.access_control = RuleBasedAccessControl({
        "catalogs": [
            {"user": "admin", "catalog": ".*", "allow": "all"},
            {"user": "analyst", "catalog": "tpch", "allow": "read-only"},
            {"user": "analyst", "catalog": "mem", "allow": "all"},
            {"user": "analyst", "catalog": "system", "allow": "read-only"},
        ],
        "tables": [
            {"user": "analyst", "catalog": "tpch", "table": "supplier",
             "allow": "none"},
        ],
    })
    return e


def _sess(e, user, catalog="tpch"):
    s = e.create_session(catalog)
    s.user = user
    return s


def test_select_rules(secured_engine):
    e = secured_engine
    assert e.execute_sql("select count(*) c from nation",
                         _sess(e, "analyst")).rows() == [(25,)]
    with pytest.raises(AccessDeniedError, match="supplier"):
        e.execute_sql("select count(*) from supplier", _sess(e, "analyst"))
    # denied table inside a join is still denied
    with pytest.raises(AccessDeniedError, match="supplier"):
        e.execute_sql("select count(*) from nation, supplier "
                      "where n_nationkey = s_nationkey", _sess(e, "analyst"))
    # an unmatched user hits the default-deny of a non-empty catalog rule list
    with pytest.raises(AccessDeniedError):
        e.execute_sql("select count(*) from nation", _sess(e, "intern"))
    assert e.execute_sql("select count(*) c from supplier",
                         _sess(e, "admin")).rows()[0][0] > 0


def test_read_only_blocks_writes(secured_engine):
    e = secured_engine
    s = _sess(e, "analyst", "mem")
    e.execute_sql("create table notes (id bigint)", s)  # mem: allow all
    e.execute_sql("insert into notes values (1)", s)
    assert e.execute_sql("select count(*) c from notes", s).rows() == [(1,)]
    # cached-plan re-run as a different user re-checks access
    with pytest.raises(AccessDeniedError):
        e.execute_sql("select count(*) c from notes", _sess(e, "intern", "mem"))


def test_show_tables_filtered(secured_engine):
    e = secured_engine
    rows = e.execute_sql("show tables", _sess(e, "analyst")).rows()
    names = [t for (t,) in rows]
    assert "nation" in names and "supplier" not in names


def test_http_auth_and_metrics(tpch_sf001):
    from trino_tpu.server.server import CoordinatorServer

    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    srv = CoordinatorServer(e, passwords={"ana": "pw1"})
    srv.start()
    try:
        # missing credentials -> 401
        req = urllib.request.Request(f"{srv.url}/v1/statement",
                                     data=b"select 1", method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=5)
        assert exc.value.code == 401
        # valid basic auth passes and the query runs
        import base64

        cred = base64.b64encode(b"ana:pw1").decode()
        req = urllib.request.Request(
            f"{srv.url}/v1/statement", data=b"select count(*) c from region",
            method="POST", headers={"Authorization": f"Basic {cred}",
                                    "X-Trino-User": "ana",
                                    "X-Trino-Catalog": "tpch"})
        out = json.loads(urllib.request.urlopen(req, timeout=30).read())
        assert out["id"]
        # GET surfaces (results, metrics, UI) are gated too: observability
        # endpoints leak SQL text, so they authenticate the principal
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{srv.url}/v1/metrics", timeout=5)
        assert exc.value.code == 401
        authed = {"Authorization": f"Basic {cred}"}
        body = urllib.request.urlopen(
            urllib.request.Request(f"{srv.url}/v1/metrics", headers=authed),
            timeout=5).read().decode()
        assert "trino_tpu_queries_total" in body
        html = urllib.request.urlopen(
            urllib.request.Request(f"{srv.url}/ui", headers=authed),
            timeout=5).read().decode()
        assert "<h1>trino-tpu</h1>" in html  # the SPA shell serves authed
    finally:
        srv.stop()


def test_client_basic_auth(tpch_sf001, tmp_path):
    """The in-tree client can speak to a password-configured server — it
    attaches Basic credentials on every request including spooled-segment
    fetches (reference: client BasicAuthInterceptor)."""
    from trino_tpu.server.client import Client, QueryError
    from trino_tpu.server.server import CoordinatorServer

    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    # tiny inline threshold forces the spooled path so _fetch_segment is
    # exercised under auth too
    srv = CoordinatorServer(e, passwords={"ana": "pw1"},
                            spool_dir=str(tmp_path / "segments"),
                            spool_threshold_rows=1)
    srv.start()
    try:
        c = Client(srv.url, catalog="tpch", user="ana", password="pw1")
        out = c.execute("select n_name from nation order by n_name limit 3")
        assert len(out.rows) == 3
        bad = Client(srv.url, catalog="tpch", user="ana", password="nope")
        with pytest.raises((QueryError, Exception)):
            bad.execute("select 1")
    finally:
        srv.stop()


def test_materialized_views(tpch_sf001):
    """CREATE/REFRESH/DROP MATERIALIZED VIEW: queries read the storage table
    (results as of the last refresh), REFRESH re-materializes (reference:
    CreateMaterializedViewTask / RefreshMaterializedViewTask + MV storage
    tables)."""
    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table src (k bigint)", s)
    e.execute_sql("insert into src values (1), (2)", s)
    e.execute_sql("create materialized view mv as "
                  "select count(*) c, sum(k) s from src", s)
    assert e.execute_sql("select c, s from mv", s).rows() == [(2, 3)]
    # base-table changes are invisible until REFRESH
    e.execute_sql("insert into src values (10)", s)
    assert e.execute_sql("select c, s from mv", s).rows() == [(2, 3)]
    e.execute_sql("refresh materialized view mv", s)
    assert e.execute_sql("select c, s from mv", s).rows() == [(3, 13)]
    # listed by SHOW TABLES, storage table hidden
    names = [t for (t,) in e.execute_sql("show tables", s).rows()]
    assert "mv" in names and "__mv_mv" not in names
    e.execute_sql("drop materialized view mv", s)
    with pytest.raises(Exception):
        e.execute_sql("select * from mv", s)


def test_grant_revoke(tpch_sf001):
    """GRANT/REVOKE against the grant-based access control: default-closed,
    privileges arrive per table per user, REVOKE removes them (reference:
    GrantTask/RevokeTask + spi/security/Privilege)."""
    from trino_tpu.spi.security import GrantBasedAccessControl

    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    e.access_control = GrantBasedAccessControl(admins=("admin",))
    admin = e.create_session("mem")
    admin.user = "admin"
    e.execute_sql("create table t1 (x bigint)", admin)
    e.execute_sql("insert into t1 values (7)", admin)
    bob = e.create_session("mem")
    bob.user = "bob"
    with pytest.raises(AccessDeniedError):
        e.execute_sql("select * from t1", bob)
    e.execute_sql("grant select on t1 to bob", admin)
    assert e.execute_sql("select x from t1", bob).rows() == [(7,)]
    with pytest.raises(AccessDeniedError):  # select does not confer insert
        e.execute_sql("insert into t1 values (8)", bob)
    e.execute_sql("grant insert on table t1 to bob", admin)
    e.execute_sql("insert into t1 values (8)", bob)
    e.execute_sql("revoke all privileges on t1 from bob", admin)
    with pytest.raises(AccessDeniedError):
        e.execute_sql("select * from t1", bob)
    # non-admins may not administer grants
    with pytest.raises(AccessDeniedError):
        e.execute_sql("grant select on t1 to eve", bob)


def test_row_filter_and_column_mask():
    """Access-control ViewExpressions (reference: spi/security
    SystemAccessControl.getRowFilters/getColumnMasks): the planner splices a
    row filter and column masks over the table per user; plans cache per
    user."""
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector
    from trino_tpu.spi.security import RuleBasedAccessControl

    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.001))
    e.access_control = RuleBasedAccessControl({
        "tables": [{"user": "analyst", "table": "nation",
                    "filter": "n_regionkey = 1",
                    "column_masks": {"n_comment": "null"}}]})
    s_admin = e.create_session("tpch")
    s_admin.user = "admin"
    s_an = e.create_session("tpch")
    s_an.user = "analyst"
    sql = "select n_name, n_comment, n_regionkey from nation order by n_name"
    admin_rows = e.execute_sql(sql, s_admin).rows()
    assert len(admin_rows) == 25
    assert any(r[1] is not None for r in admin_rows)
    rows = e.execute_sql(sql, s_an).rows()
    assert len(rows) == 5
    assert {int(r[2]) for r in rows} == {1}
    assert all(r[1] is None for r in rows)
    # same SQL again for the unfiltered user: per-user plan cache keys keep
    # the filtered plan from leaking across users
    assert len(e.execute_sql(sql, s_admin).rows()) == 25
