"""Access control SPI, rule engine, HTTP auth, metrics endpoint, web UI
(reference: spi/security SystemAccessControl + file-based access control;
JmxOpenMetricsModule; core/trino-web-ui's cluster overview)."""

import json
import urllib.request

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.spi.security import (AccessDeniedError, RuleBasedAccessControl)


@pytest.fixture()
def secured_engine(tpch_sf001):
    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    e.register_catalog("mem", MemoryConnector())
    e.access_control = RuleBasedAccessControl({
        "catalogs": [
            {"user": "admin", "catalog": ".*", "allow": "all"},
            {"user": "analyst", "catalog": "tpch", "allow": "read-only"},
            {"user": "analyst", "catalog": "mem", "allow": "all"},
            {"user": "analyst", "catalog": "system", "allow": "read-only"},
        ],
        "tables": [
            {"user": "analyst", "catalog": "tpch", "table": "supplier",
             "allow": "none"},
        ],
    })
    return e


def _sess(e, user, catalog="tpch"):
    s = e.create_session(catalog)
    s.user = user
    return s


def test_select_rules(secured_engine):
    e = secured_engine
    assert e.execute_sql("select count(*) c from nation",
                         _sess(e, "analyst")).rows() == [(25,)]
    with pytest.raises(AccessDeniedError, match="supplier"):
        e.execute_sql("select count(*) from supplier", _sess(e, "analyst"))
    # denied table inside a join is still denied
    with pytest.raises(AccessDeniedError, match="supplier"):
        e.execute_sql("select count(*) from nation, supplier "
                      "where n_nationkey = s_nationkey", _sess(e, "analyst"))
    # an unmatched user hits the default-deny of a non-empty catalog rule list
    with pytest.raises(AccessDeniedError):
        e.execute_sql("select count(*) from nation", _sess(e, "intern"))
    assert e.execute_sql("select count(*) c from supplier",
                         _sess(e, "admin")).rows()[0][0] > 0


def test_read_only_blocks_writes(secured_engine):
    e = secured_engine
    s = _sess(e, "analyst", "mem")
    e.execute_sql("create table notes (id bigint)", s)  # mem: allow all
    e.execute_sql("insert into notes values (1)", s)
    assert e.execute_sql("select count(*) c from notes", s).rows() == [(1,)]
    # cached-plan re-run as a different user re-checks access
    with pytest.raises(AccessDeniedError):
        e.execute_sql("select count(*) c from notes", _sess(e, "intern", "mem"))


def test_show_tables_filtered(secured_engine):
    e = secured_engine
    rows = e.execute_sql("show tables", _sess(e, "analyst")).rows()
    names = [t for (t,) in rows]
    assert "nation" in names and "supplier" not in names


def test_http_auth_and_metrics(tpch_sf001):
    from trino_tpu.server.server import CoordinatorServer

    e = Engine()
    e.register_catalog("tpch", tpch_sf001)
    srv = CoordinatorServer(e, passwords={"ana": "pw1"})
    srv.start()
    try:
        # missing credentials -> 401
        req = urllib.request.Request(f"{srv.url}/v1/statement",
                                     data=b"select 1", method="POST")
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(req, timeout=5)
        assert exc.value.code == 401
        # valid basic auth passes and the query runs
        import base64

        cred = base64.b64encode(b"ana:pw1").decode()
        req = urllib.request.Request(
            f"{srv.url}/v1/statement", data=b"select count(*) c from region",
            method="POST", headers={"Authorization": f"Basic {cred}",
                                    "X-Trino-User": "ana",
                                    "X-Trino-Catalog": "tpch"})
        out = json.loads(urllib.request.urlopen(req, timeout=30).read())
        assert out["id"]
        # GET surfaces (results, metrics, UI) are gated too: observability
        # endpoints leak SQL text, so they authenticate the principal
        with pytest.raises(urllib.error.HTTPError) as exc:
            urllib.request.urlopen(f"{srv.url}/v1/metrics", timeout=5)
        assert exc.value.code == 401
        authed = {"Authorization": f"Basic {cred}"}
        body = urllib.request.urlopen(
            urllib.request.Request(f"{srv.url}/v1/metrics", headers=authed),
            timeout=5).read().decode()
        assert "trino_tpu_queries_total" in body
        html = urllib.request.urlopen(
            urllib.request.Request(f"{srv.url}/ui", headers=authed),
            timeout=5).read().decode()
        assert "trino-tpu coordinator" in html
    finally:
        srv.stop()
