"""Query-level profiling: span trees with per-operator device-boundary
attribution, cluster counter flow surfaces, and dispatch-latency histograms.

What round 7 added on top of the round-6 QueryCounters:
- every ``_jit`` dispatch / ``_host`` pull carries a call-site tag and lands
  under the active operator scope -> ``counters.sites`` and the executor's
  per-node ``boundary`` dict (EXPLAIN ANALYZE attribution);
- the engine's Tracer is ACTIVATED per statement, so executor internals emit
  dispatch spans, prefetch-thread spans (explicit cross-thread parent), and
  exchange-segment spans under the query's root span
  (``engine.last_query_trace``, ``GET /v1/query/{id}/trace`` OTLP JSON);
- dispatch wall times feed fixed-bucket histograms (per query + engine
  totals) exported as a proper Prometheus histogram in ``/v1/metrics``.

The SF1 acceptance tests (warm q3 span tree, warm q9 EXPLAIN ANALYZE
attribution) live in tests/test_query_budgets.py with the other SF1 runs;
this module covers the same invariants at test scale plus the HTTP and
format surfaces.
"""

import json
import re
import threading
import time
import urllib.request

import pytest

from trino_tpu.execution.tracing import (LATENCY_BUCKETS_S, LatencyHistogram,
                                         QueryCounters, Tracer, span_dict,
                                         spans_to_otlp)


# ---------------------------------------------------------------- unit layer
def test_tracer_explicit_parent_across_threads():
    """Satellite: thread-local parenting orphaned background-thread spans;
    ``parent=`` carries the query-thread span across explicitly."""
    tr = Tracer()
    out = {}
    with tr.span("root", trace_id="q") as root:
        parent = tr.current()
        assert parent is root

        def worker():
            with tr.span("bg", parent=parent) as s:
                out["trace_id"] = s.trace_id
                out["parent_id"] = s.parent_id

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        # without parent=, the background thread has NO current span -> orphan
        def orphan():
            with tr.span("orphan") as s:
                out["orphan_parent"] = s.parent_id

        t2 = threading.Thread(target=orphan)
        t2.start()
        t2.join()
    assert out["parent_id"] == root.span_id
    assert out["trace_id"] == "q"  # trace id inherited through the parent
    assert out["orphan_parent"] is None
    names = {s.name for s in tr.spans_for("q")}
    assert names == {"root", "bg"}


def test_latency_histogram_buckets_and_quantiles():
    h = LatencyHistogram()
    for v in (0.0002, 0.0002, 0.003, 0.2, 20.0):
        h.record(v)
    d = h.as_dict()
    assert d["count"] == 5 and sum(d["buckets"]) == 5
    assert d["buckets"][-1] == 1  # 20s -> +Inf bucket
    assert h.quantile(0.5) <= 0.005
    assert h.quantile(0.99) == LATENCY_BUCKETS_S[-1]
    # merge_dict (the cluster wire form) preserves totals
    h2 = LatencyHistogram()
    h2.merge_dict(d)
    assert h2.as_dict() == d


def test_counters_dict_roundtrip_and_merge():
    a = QueryCounters()
    a.device_dispatches = 3
    a.host_transfers = 2
    a.host_bytes_pulled = 100
    a.sites["Agg#0/step"] = {"dispatches": 3, "transfers": 0, "bytes": 0}
    a.sites["Sort#1/sort.pull"] = {"dispatches": 0, "transfers": 2,
                                   "bytes": 100}
    a.dispatch_latency.record(0.01)
    b = QueryCounters.from_dict(a.as_dict())
    assert b.as_dict() == a.as_dict()
    b.merge_dict(a.as_dict())
    assert b.device_dispatches == 6
    assert b.sites["Agg#0/step"]["dispatches"] == 6
    assert b.dispatch_latency.total == 2


def test_spans_to_otlp_shape():
    tr = Tracer()
    with tr.span("query", trace_id="qx", sql="select 1"):
        with tr.span("execution"):
            tr.add_completed("dispatch", 0.005, site="stream.page")
    payload = spans_to_otlp(tr.spans_for("qx"))
    spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert {s["name"] for s in spans} == {"query", "execution", "dispatch"}
    by_name = {s["name"]: s for s in spans}
    assert by_name["query"]["parentSpanId"] == ""
    assert by_name["execution"]["parentSpanId"] == \
        by_name["query"]["spanId"]
    assert by_name["dispatch"]["parentSpanId"] == \
        by_name["execution"]["spanId"]
    for s in spans:
        assert re.fullmatch(r"[0-9a-f]{32}", s["traceId"])
        assert re.fullmatch(r"[0-9a-f]{16}", s["spanId"])
        assert int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"])
    # dicts (the worker-span wire form) render identically to Span objects
    again = spans_to_otlp([span_dict(s) for s in tr.spans_for("qx")])
    assert again == payload


# ---------------------------------------------------------------- engine layer
QUERY = """select l_returnflag, sum(l_quantity) q, count(*) c
           from lineitem where l_shipdate <= date '1998-09-02'
           group by l_returnflag order by l_returnflag"""


def test_per_site_sums_equal_totals(engine):
    s = engine.create_session("tpch")
    engine.execute_sql(QUERY, s)
    engine.execute_sql(QUERY, s)  # warm
    c = engine.last_query_counters
    assert c.device_dispatches > 0 and c.sites
    assert sum(v["dispatches"] for v in c.sites.values()) \
        == c.device_dispatches
    assert sum(v["transfers"] for v in c.sites.values()) == c.host_transfers
    assert sum(v["bytes"] for v in c.sites.values()) == c.host_bytes_pulled
    # every dispatch was timed into the per-query histogram
    assert c.dispatch_latency.total == c.device_dispatches
    # attribution keys carry the operator scope ("<Op>#<k>/<site>")
    assert any("/" in k and "#" in k.split("/")[0] for k in c.sites)


def test_span_tree_shape_and_parent_integrity(engine):
    s = engine.create_session("tpch")
    # a unique alias makes a fresh plan-cache key: this run is genuinely COLD
    # even on the shared module engine, so the planner span must appear
    engine.execute_sql(QUERY.replace("sum(l_quantity) q", "sum(l_quantity) q0"),
                       s)
    cold = engine.last_query_trace
    cold_names = [sp["name"] for sp in cold["spans"]]
    assert "planner" in cold_names and "query" in cold_names
    engine.execute_sql(QUERY, s)  # ensure the shared-key plan exists
    engine.execute_sql(QUERY, s)  # warm: cached plan, execution span present
    t = engine.last_query_trace
    names = [sp["name"] for sp in t["spans"]]
    assert names.count("query") == 1
    assert "execution" in names
    assert names.count("dispatch") == engine.last_query_counters \
        .device_dispatches
    ids = {sp["span_id"] for sp in t["spans"]}
    roots = [sp for sp in t["spans"] if sp["parent_id"] is None]
    assert len(roots) == 1 and roots[0]["name"] == "query"
    for sp in t["spans"]:
        if sp["parent_id"] is not None:
            assert sp["parent_id"] in ids, sp
        assert sp["end_s"] is not None
    assert t["root_span_s"] > 0


def test_prefetch_spans_parent_across_thread():
    """The coalescing prefetch producer runs on a background thread; its span
    must still parent into the query's tree (explicit parent handoff)."""
    from trino_tpu import Engine
    from trino_tpu.connectors.tpch import TpchConnector

    e = Engine()
    # small splits -> multi-split scan -> the dispatch-coalescing double
    # buffer engages its producer thread
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    s = e.create_session("tpch")
    e.execute_sql(QUERY, s)
    e.execute_sql(QUERY, s)
    qid = e.last_query_trace["query_id"]
    # the producer's span closes on ITS thread right after the consumer
    # drains; allow it a beat to land in the tracer
    spans = []
    for _ in range(50):
        spans = e.tracer.spans_for(qid)
        if any(sp.name == "prefetch" for sp in spans):
            break
        time.sleep(0.02)
    prefetch = [sp for sp in spans if sp.name == "prefetch"]
    assert prefetch, [sp.name for sp in spans]
    ids = {sp.span_id for sp in spans}
    for sp in prefetch:
        assert sp.parent_id in ids  # NOT an orphan
        assert sp.attributes.get("pages", 0) > 0
    e._invalidate()


def test_explain_analyze_per_operator_attribution(engine):
    """Per-node [boundary: ...] rows and per-site lines sum to the query's
    counter totals (the small-scale version of the SF1 q9 acceptance test in
    test_query_budgets.py)."""
    r = engine.execute_sql(f"explain analyze {QUERY}",
                           engine.create_session("tpch"))
    text = "\n".join(str(row[0]) for row in r.rows())
    c = engine.last_query_counters
    assert "Device boundary:" in text
    m = re.search(r"Device boundary: (\d+) dispatches, (\d+) host transfers, "
                  r"(\d+) bytes pulled", text)
    assert m, text
    assert (int(m.group(1)), int(m.group(2)), int(m.group(3))) == \
        (c.device_dispatches, c.host_transfers, c.host_bytes_pulled)
    sites = re.findall(r"site (\S+): (\d+) dispatches, (\d+) transfers, "
                       r"(\d+) bytes", text)
    assert sites, text
    assert sum(int(d) for _, d, _t, _b in sites) == c.device_dispatches
    assert sum(int(b) for _, _d, _t, b in sites) == c.host_bytes_pulled
    # per-operator rows on the plan nodes themselves
    op_rows = re.findall(r"\[boundary: (\d+) dispatches, (\d+) transfers, "
                         r"(\d+) bytes\]", text)
    assert op_rows, text


def test_query_completed_event_carries_boundary_profile(engine):
    from trino_tpu.execution.eventlistener import EventListener

    got = []

    class L(EventListener):
        def query_completed(self, event):
            got.append(event)

    listener = L()
    engine.event_listeners.add(listener)
    try:
        s = engine.create_session("tpch")
        engine.execute_sql("select count(*) from nation", s)
        ev = got[-1]
        assert ev.counters is not None
        assert ev.counters["device_dispatches"] > 0
        assert ev.counters["sites"]
        assert ev.root_span_s is not None and ev.root_span_s > 0
        # a statement that executes no plan leaves counters unset
        engine.execute_sql("set session dispatch_batch = 2", s)
        assert got[-1].counters is None
        assert got[-1].root_span_s is not None
    finally:
        engine.event_listeners.listeners.remove(listener)


# ---------------------------------------------------------------- HTTP layer
def _parse_prometheus(body: str) -> dict:
    """Strict-ish Prometheus text-format parse: every sample line must match
    the exposition grammar, every sampled metric must have a # TYPE, label
    values must be quoted/escaped.  Returns {metric: [(labels, value)]}."""
    types, helps, samples = {}, {}, {}
    sample_re = re.compile(
        r'^([a-zA-Z_:][a-zA-Z0-9_:]*)'
        r'(?:\{((?:[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\]|\\.)*",?)*)\})?'
        r' (-?(?:\d+\.?\d*(?:[eE][+-]?\d+)?|NaN|[+-]Inf))$')
    for line in body.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, kind = rest.split()
            types[name] = kind
            continue
        if line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            helps[rest.split()[0]] = rest
            continue
        assert not line.startswith("#"), f"unparseable comment: {line!r}"
        m = sample_re.match(line)
        assert m, f"unparseable sample line: {line!r}"
        name = m.group(1)
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        assert name in types or base in types, \
            f"sample {name} has no # TYPE"
        labels = dict(re.findall(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"',
                                 m.group(2) or ""))
        samples.setdefault(name, []).append((labels, float(m.group(3))))
    return {"types": types, "helps": helps, "samples": samples}


@pytest.fixture()
def profiling_server(engine):
    from trino_tpu.server.server import CoordinatorServer

    srv = CoordinatorServer(engine, port=0)
    srv.start()
    yield srv
    srv.stop()


def test_metrics_histogram_passes_format_check(profiling_server, engine):
    from trino_tpu.server import Client

    c = Client(profiling_server.url, catalog="tpch")
    c.execute("select count(*) from nation")
    body = urllib.request.urlopen(
        profiling_server.url + "/v1/metrics", timeout=10).read().decode()
    parsed = _parse_prometheus(body)
    # HELP/TYPE metadata present (satellite: bare counter lines rejected by
    # stricter scrapers)
    assert parsed["types"]["trino_tpu_queries_total"] == "counter"
    assert "trino_tpu_device_dispatches_total" in parsed["helps"]
    # the dispatch-latency histogram: TYPE histogram, cumulative buckets
    # ending at +Inf == _count, _sum present
    assert parsed["types"]["trino_tpu_dispatch_latency_seconds"] == \
        "histogram"
    buckets = parsed["samples"]["trino_tpu_dispatch_latency_seconds_bucket"]
    assert buckets[-1][0]["le"] == "+Inf"
    values = [v for _, v in buckets]
    assert values == sorted(values), "histogram buckets must be cumulative"
    count = parsed["samples"]["trino_tpu_dispatch_latency_seconds_count"][0][1]
    assert buckets[-1][1] == count and count > 0
    assert parsed["samples"]["trino_tpu_dispatch_latency_seconds_sum"][0][1] \
        >= 0
    # per-site series carry escaped label values
    assert any(s[0].get("site")
               for s in parsed["samples"]
               .get("trino_tpu_site_dispatches_total", []))


def test_label_escaping():
    from trino_tpu.server.server import CoordinatorServer

    esc = CoordinatorServer._escape_label
    assert esc('a"b\\c\nd') == 'a\\"b\\\\c\\nd'


def test_trace_endpoint_round_trip(profiling_server, engine):
    from trino_tpu.server import Client

    c = Client(profiling_server.url, catalog="tpch")
    c.execute("select count(*) from region")
    # find the server-side query id (the most recent FINISHED one)
    qs = [q for q in profiling_server.queries.values()
          if q.state == "FINISHED"]
    qid = sorted(qs, key=lambda q: q.created_at)[-1].query_id
    payload = json.loads(urllib.request.urlopen(
        profiling_server.url + f"/v1/query/{qid}/trace",
        timeout=10).read().decode())
    spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    names = {s["name"] for s in spans}
    assert "query" in names and "dispatch" in names
    roots = [s for s in spans if s["parentSpanId"] == ""]
    assert len(roots) == 1 and roots[0]["name"] == "query"
    # unknown id -> 404
    with pytest.raises(urllib.error.HTTPError) as exc:
        urllib.request.urlopen(
            profiling_server.url + "/v1/query/nope/trace", timeout=10)
    assert exc.value.code == 404


def test_engine_query_id_trace_lookup(profiling_server, engine):
    """The trace endpoint also resolves ENGINE query ids (query_N) straight
    from the live tracer — the embedded-engine escape hatch."""
    s = engine.create_session("tpch")
    engine.execute_sql("select count(*) from region", s)
    qid = engine.last_query_trace["query_id"]
    payload = json.loads(urllib.request.urlopen(
        profiling_server.url + f"/v1/query/{qid}/trace",
        timeout=10).read().decode())
    spans = payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    assert any(s["name"] == "query" for s in spans)


# ------------------------------------------------- in-flight registry (round 8)
def test_inflight_registry_entry_lifecycle():
    """Entries carry the same "<Op>#<k>/<site>" label the counters' site
    table uses, plus query id / thread / start time, and retire on exit."""
    from trino_tpu.execution import tracing

    reg = tracing.InflightRegistry()
    with tracing.track_inflight(reg), tracing.query_scope("query_77"):
        assert reg.depth() == 0
        with tracing.operator_scope("Aggregate#3", None):
            with tracing.inflight("dispatch", site="dstep"):
                snap = reg.snapshot()
                assert len(snap) == 1 and reg.depth() == 1
                (e,) = snap
                assert e["label"] == "Aggregate#3/dstep"
                assert e["kind"] == "dispatch" and e["site"] == "dstep"
                assert e["op"] == "Aggregate#3"
                assert e["query_id"] == "query_77"
                assert e["thread_id"] == threading.get_ident()
                assert e["elapsed_s"] >= 0
    assert reg.depth() == 0
    # without an op scope the label degrades to the bare site
    tok = reg.enter("host_pull", "agg.pull")
    assert reg.snapshot()[0]["label"] == "agg.pull"
    reg.exit(tok)
    assert reg.depth() == 0


def test_stall_watchdog_fake_clock_report_shape():
    """Fake-clock stall detection: an entry 'aged' past the threshold yields
    a structured report (label, query id, elapsed, stuck thread's stack,
    extra memory info) and a live 'stalled' verdict; it clears on exit."""
    from trino_tpu.execution import tracing

    reg = tracing.InflightRegistry()
    got = []
    wd = tracing.StallWatchdog(registry=reg, stall_s=5.0, kill_s=0,
                               on_stall=got.append,
                               extra_info=lambda: {"memory": [{"pool": "p0"}]})
    assert wd.enabled
    with tracing.track_inflight(reg), tracing.query_scope("query_42"):
        with tracing.operator_scope("HashJoin#2", None):
            with tracing.inflight("dispatch", site="probe.step"):
                now = time.monotonic() + 100.0  # fake clock: entry is 100s old
                report = wd.check(now=now)
                assert report is not None and wd.last_report is report
                assert wd.stalled_now == 1 and got == [report]
                assert wd.verdict(now=now) == ("stalled", 1)
                assert report["threshold_s"] == 5.0
                assert report["inflight_depth"] == 1
                assert report["memory"] == [{"pool": "p0"}]
                (e,) = report["stalled"]
                assert e["label"] == "HashJoin#2/probe.step"
                assert e["query_id"] == "query_42"
                assert e["elapsed_s"] >= 100
                # the stuck thread's live stack is in the report (it is THIS
                # thread, so our own frame must appear)
                assert e["stack"] and "test_stall_watchdog" in e["stack"]
    # entry retired -> healthy again, gauge drops
    assert wd.check(now=time.monotonic() + 100.0) is None
    assert wd.stalled_now == 0
    assert wd.verdict()[0] == "ok"
    # a disabled watchdog (no threshold) never reports
    off = tracing.StallWatchdog(registry=reg, stall_s=0)
    assert not off.enabled and off.check() is None and off.verdict() == ("ok", 0)


def test_slow_dispatch_stall_report_and_status_flip(profiling_server, engine):
    """Acceptance: a deliberately-slowed dispatch (test hook) produces a
    stall report naming the correct "<Op>#<k>/<site>" within one watchdog
    period, and /v1/status reads "stalled" WHILE the dispatch hangs."""
    from trino_tpu.execution import tracing

    wd = engine.stall_watchdog
    saved = (wd.stall_s, wd.poll_s)
    wd.stall_s, wd.poll_s = 0.05, 0.01
    engine.last_stall_report = None
    status_seen = []

    def hook(site):
        # slow only the first two dispatches (enough for >1 watchdog period)
        # and snapshot /v1/status from INSIDE the stall
        if len(status_seen) < 2:
            time.sleep(0.2)
            status_seen.append(json.loads(urllib.request.urlopen(
                profiling_server.url + "/v1/status", timeout=10)
                .read().decode()))

    try:
        s = engine.create_session("tpch")
        # prewarm BEFORE arming the hook: the slowed dispatches must be
        # warm (seen signatures) — a first-seen dispatch is flagged
        # `compiling` and the round-17 compile-aware watchdog would verdict
        # "compiling" instead of producing the stall report this test pins
        engine.execute_sql(QUERY, s)
        wd.start()
        tracing.DISPATCH_TEST_HOOK = hook
        engine.execute_sql(QUERY, s)
    finally:
        tracing.DISPATCH_TEST_HOOK = None
        wd.stop()
        wd.stall_s, wd.poll_s = saved
    report = engine.last_stall_report
    assert report is not None, "watchdog never reported"
    labels = [e["label"] for e in report["stalled"]]
    # the stuck site carries full operator attribution: "<Op>#<k>/<site>"
    assert any("#" in lbl.split("/")[0] and "/" in lbl for lbl in labels), \
        labels
    assert any(e["stack"] for e in report["stalled"])
    assert report.get("memory"), report.keys()
    # the live status surface flipped while the dispatch hung
    st = status_seen[0]
    assert st["health"]["status"] == "stalled"
    assert st["health"]["stalled"] >= 1
    assert any(f["kind"] == "dispatch" for f in st["inflight"])
    # the executing query is visible as RUNNING with its in-flight entries
    running = [q for q in st["queries"] if q["state"] == "RUNNING"]
    assert running and any(q["inflight"] for q in running)
    # after the query finishes the verdict clears (watchdog still enabled at
    # the lowered threshold inside the finally's restore window is fine —
    # recompute against the restored config)
    assert engine.health()["status"] == "ok"


def test_status_endpoint_shape(profiling_server, engine):
    from trino_tpu.server import Client

    Client(profiling_server.url, catalog="tpch").execute(
        "select count(*) from nation")
    st = json.loads(urllib.request.urlopen(
        profiling_server.url + "/v1/status", timeout=10).read().decode())
    assert st["health"]["status"] == "ok"
    assert st["health"]["watchdog"]["enabled"] in (True, False)
    assert isinstance(st["inflight"], list)
    assert isinstance(st["queries"], list)
    # memory pools expose the MemoryPool snapshot dict, labeled
    assert st["memory"], "no executor pools surfaced"
    assert {"pool", "reserved", "max_bytes", "free"} <= set(st["memory"][0])


def test_metrics_stall_memory_and_resource_group_gauges(profiling_server,
                                                        engine):
    """Round-8 satellite: MemoryPool snapshots + resource-group queue depths
    + the stalled/in-flight gauges reach /v1/metrics as labeled gauges."""
    from trino_tpu.server import Client

    Client(profiling_server.url, catalog="tpch").execute(
        "select count(*) from region")
    body = urllib.request.urlopen(
        profiling_server.url + "/v1/metrics", timeout=10).read().decode()
    parsed = _parse_prometheus(body)
    assert parsed["types"]["trino_tpu_stalled_dispatches"] == "gauge"
    assert parsed["samples"]["trino_tpu_stalled_dispatches"][0][1] == 0
    assert parsed["types"]["trino_tpu_inflight_entries"] == "gauge"
    assert parsed["types"]["trino_tpu_memory_reserved_bytes"] == "gauge"
    pools = parsed["samples"]["trino_tpu_memory_reserved_bytes"]
    assert pools and all(lbl.get("pool") for lbl, _ in pools)
    assert parsed["samples"]["trino_tpu_memory_max_bytes"][0][1] > 0
    assert parsed["types"]["trino_tpu_resource_group_running"] == "gauge"
    groups = parsed["samples"]["trino_tpu_resource_group_queued"]
    assert groups and all(lbl.get("group") for lbl, _ in groups)
    # round-16 satellite: the flight-recorder series ride the same strict
    # exposition (records/bytes gauges, lifetime + stitched-span counters)
    assert parsed["types"]["trino_tpu_flight_records"] == "gauge"
    assert parsed["samples"]["trino_tpu_flight_records"][0][1] > 0
    assert parsed["types"]["trino_tpu_flight_spans_total"] == "counter"
    assert parsed["types"]["trino_tpu_flight_worker_spans_total"] == "counter"


def test_runtime_queries_boundary_columns(engine):
    """Round-8 satellite: system.runtime.queries exposes device_dispatches /
    host_bytes_pulled / elapsed_s so a SQL client sees spend without curling
    /v1/metrics."""
    s = engine.create_session("tpch")
    engine.execute_sql("select count(*) from nation", s)
    r = engine.execute_sql(
        "select query_id, state, device_dispatches, host_bytes_pulled, "
        "elapsed_s from system.queries", s)
    rows = r.rows()
    assert rows
    finished = [row for row in rows if row[1] == "FINISHED"
                and row[2] is not None]
    assert finished, rows
    qid, _, dd, hb, elapsed = finished[-1]
    assert dd > 0 and hb > 0 and elapsed > 0
