"""EXISTS in expression position via MARK joins (reference:
SubqueryPlanner's correlatedExists -> SemiJoinNode semiJoinOutput symbol;
executor 'mark' kind appends the matched boolean channel)."""

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.memory import MemoryConnector


@pytest.fixture(scope="module")
def meng():
    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table c (ck bigint, nm varchar)", s)
    e.execute_sql("create table w (wk bigint)", s)
    e.execute_sql("create table g (gk bigint)", s)
    e.execute_sql("insert into c values (1,'a'), (2,'b'), (3,'c'), (4,'d')", s)
    e.execute_sql("insert into w values (1), (3)", s)
    e.execute_sql("insert into g values (2), (3)", s)
    return e, s


def _col(meng, sql):
    e, s = meng
    return list(e.execute_sql(sql, s).to_pandas().iloc[:, 0])


def test_or_of_two_exists(meng):
    assert _col(meng, """select ck from c
        where exists (select 1 from w where wk = c.ck)
           or exists (select 1 from g where gk = c.ck)
        order by ck""") == [1, 2, 3]


def test_not_over_or_of_exists(meng):
    assert _col(meng, """select ck from c
        where not (exists (select 1 from w where wk = c.ck)
                or exists (select 1 from g where gk = c.ck))""") == [4]


def test_exists_or_plain_predicate(meng):
    assert _col(meng, """select ck from c
        where ck = 4 or exists (select 1 from w where wk = c.ck)
        order by ck""") == [1, 3, 4]


def test_exists_inside_case(meng):
    assert _col(meng, """select ck from c
        where case when exists (select 1 from w where wk = c.ck)
              then 1 else 0 end = 1 order by ck""") == [1, 3]


def test_negated_exists_under_or(meng):
    assert _col(meng, """select ck from c
        where (not exists (select 1 from w where wk = c.ck)) or ck = 1
        order by ck""") == [1, 2, 4]


def test_uncorrelated_exists_under_or_folds(meng):
    assert _col(meng, """select ck from c
        where exists (select 1 from w where wk > 100) or ck = 2""") == [2]


def test_mark_mixes_with_in_subquery(meng):
    assert _col(meng, """select ck from c
        where ck in (select gk from g)
           or exists (select 1 from w where wk = c.ck)
        order by ck""") == [1, 2, 3]


def test_plain_exists_still_semi_join(meng):
    # top-level EXISTS must keep the semi-join path (no mark overhead)
    assert _col(meng, """select ck from c
        where exists (select 1 from w where wk = c.ck)
        order by ck""") == [1, 3]


def test_select_star_hides_mark_channel(meng):
    """SELECT * must not leak the synthetic $markN channel (review catch)."""
    e, s = meng
    r = e.execute_sql("""select * from c
        where exists (select 1 from w where wk = c.ck) or ck = 2
        order by ck""", s).to_pandas()
    assert list(r.columns) == ["ck", "nm"]
    assert list(r["ck"]) == [1, 2, 3]


def test_or_of_in_subqueries_still_works(meng):
    """Nested IN-subqueries without EXISTS keep the eager fold (review
    catch: the deepened routing must not break them)."""
    assert _col(meng, """select ck from c
        where ck in (select wk from w) or ck in (select gk from g)
        order by ck""") == [1, 2, 3]


def test_ungrouped_aggregate_exists_constant_true(meng):
    """EXISTS over an ungrouped aggregate is constant-true even in
    expression position (review catch)."""
    assert _col(meng, """select ck from c
        where ck = 4 or exists (select max(wk) from w where wk = c.ck)
        order by ck""") == [1, 2, 3, 4]


def test_grouped_exists_in_expression_position(meng):
    assert _col(meng, """select ck from c
        where ck = 4 or exists (select wk from w where wk = c.ck group by wk)
        order by ck""") == [1, 3, 4]


def test_exists_in_select_list(meng):
    """EXISTS as a projection expression (CASE WHEN EXISTS ... in SELECT)."""
    e, s = meng
    r = e.execute_sql("""select ck,
        case when exists (select 1 from w where wk = c.ck)
             then 'w' else 'x' end tag from c order by ck""", s).to_pandas()
    assert list(r["tag"]) == ["w", "x", "w", "x"]
    assert list(r.columns) == ["ck", "tag"]
    r = e.execute_sql("""select ck,
        exists (select 1 from g where gk = c.ck) m from c order by ck""",
        s).to_pandas()
    assert [bool(x) for x in r["m"]] == [False, True, True, False]


def test_correlated_scalar_in_select_list(meng):
    """Correlated scalar aggregates project through the left-join
    decorrelation channel (reference:
    TransformCorrelatedScalarAggregationToJoin in projection position)."""
    e, s = meng
    e.execute_sql("create table b2 (k bigint, v bigint)", s)
    e.execute_sql("insert into b2 values (1, 10), (1, 20), (3, 5)", s)
    r = e.execute_sql("select ck, (select sum(v) from b2 where b2.k = c.ck) sv "
                      "from c order by ck", s).to_pandas()
    vals = [None if x != x or x is None else int(x) for x in r["sv"]]
    assert vals == [30, None, 5, None]
    r = e.execute_sql("select ck, (select count(*) from b2 where b2.k = c.ck) n "
                      "from c order by ck", s).to_pandas()
    assert list(r["n"]) == [2, 0, 1, 0]
    r = e.execute_sql("select * from c where ck in (select k from b2) order by ck",
                      s).to_pandas()
    assert list(r.columns) == ["ck", "nm"]
