"""Plan-actuals history (round 15, execution/history.py): persistent
est-vs-actual cardinality records per plan node.

What these tests pin:
- records MERGE across pooled executors (concurrent statements), across warm
  re-executions of one cached plan, and across the in-process cluster harvest
  (worker task snapshots re-anchored at the fragment root's full-plan path);
- a deliberately mis-estimating query (correlated range predicates the CBO
  multiplies as independent) lands a >1 over-estimate ratio in the store, in
  EXPLAIN ANALYZE's "Misestimates:" summary, in system.runtime.plan_history,
  and on /v1/metrics;
- the feed is FREE at the device boundary: a warm re-execution bumps
  ``executions`` without changing the statement's dispatch/pull counters
  (the zero-extra-dispatches invariant test_query_budgets enforces at SF1 —
  its ceilings are unchanged with the store enabled);
- the round-8 double-arm hazard is fixed: a second armed watchdog over the
  same in-flight registry skips sampling instead of racing.
"""

import threading

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.execution import history as H

AGG_Q = """select l_returnflag, l_linestatus, sum(l_quantity) qty, count(*) c
           from lineitem where l_shipdate <= date '1998-09-02'
           group by l_returnflag, l_linestatus
           order by l_returnflag, l_linestatus"""

# correlated range predicates: the CBO multiplies the two selectivities as
# independent (~1/3 x ~3%), but the conjunction is unsatisfiable — the
# canonical over-estimate
MIS_Q = ("select c_custkey from customer "
         "where c_custkey > 1000 and c_custkey < 50")


def _engine():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    return e


@pytest.fixture(scope="module")
def engine():
    return _engine()


# ------------------------------------------------------------------ unit layer
def test_node_paths_structural_and_translation():
    from trino_tpu.page import Field, Schema
    from trino_tpu.sql import plan as P
    from trino_tpu.types import BIGINT

    sch = Schema((Field("a", BIGINT),))
    scan = P.TableScan("c", "t", ("a",), sch)
    filt = P.Filter(scan, None)
    lim = P.Limit(filt, 5)
    paths = H.plan_node_paths(lim)
    assert paths[id(lim)] == "Limit#0"
    assert paths[id(filt)] == "Filter#0.0"
    assert paths[id(scan)] == "TableScan#0.0.0"
    # structurally identical trees -> identical paths (the merge property)
    again = P.Limit(P.Filter(P.TableScan("c", "t", ("a",), sch), None), 5)
    assert sorted(H.plan_node_paths(again).values()) == \
        sorted(paths.values())
    # fragment-relative chains re-anchor by prefix composition
    assert H.translate_path("Filter#0", "0.2") == "Filter#0.2"
    assert H.translate_path("Filter#0.1.0", "0.2") == "Filter#0.2.1.0"


def test_misestimate_arithmetic():
    ratio, d = H.misestimate(100, 10)
    assert ratio == 10.0 and d == "over"
    ratio, d = H.misestimate(10, 100)
    assert ratio == 10.0 and d == "under"
    assert H.misestimate(7, 7) == (1.0, "exact")
    ratio, d = H.misestimate(50, 0)  # empty actual: denominator clamps at 1
    assert ratio == 50.0 and d == "over"


def test_store_bounded_and_lru():
    st = H.PlanHistoryStore(max_plans=2)
    rec = {"op": "Filter", "est_rows": 10.0, "actual_rows": 5, "wall_s": 0.0,
           "spilled_bytes": 0, "spill_tiers": {}, "cache_hits": 0}
    for fp in ("a", "b", "c"):
        st.record(fp, {"Filter#0": dict(rec)})
    assert st.get("a") is None  # oldest evicted
    assert st.get("b") is not None and st.get("c") is not None
    st.record("b", {"Filter#0": dict(rec)})  # touch b, then add d -> c evicts
    st.record("d", {"Filter#0": dict(rec)})
    assert st.get("c") is None and st.get("b") is not None
    disabled = H.PlanHistoryStore(max_plans=0)
    assert disabled.record("x", {"Filter#0": dict(rec)}) is None
    assert not disabled.enabled


def test_store_ewma_and_misestimate_counter():
    st = H.PlanHistoryStore(max_plans=4)
    mk = lambda a: {"Agg#0": {"op": "Agg", "est_rows": 100.0,
                              "actual_rows": a, "wall_s": 0.1,
                              "spilled_bytes": 0, "spill_tiers": {},
                              "cache_hits": 0}}
    st.record("f", mk(10))
    node = st.get("f")["nodes"]["Agg#0"]
    assert node["actual_rows_ewma"] == 10.0  # first observation seeds
    assert node["misestimate_ratio"] == 10.0 and node["direction"] == "over"
    assert st.misestimates_total == 1
    st.record("f", mk(30))
    node = st.get("f")["nodes"]["Agg#0"]
    assert node["executions"] == 2 and node["actual_rows"] == 30
    assert node["actual_rows_ewma"] == pytest.approx(0.25 * 30 + 0.75 * 10)
    assert st.misestimates_total == 2
    assert st.worst_ratio() == node["misestimate_ratio"]


# ------------------------------------------------------------------ engine layer
def test_records_accumulate_across_pooled_executors_and_warm_runs(engine):
    ph = engine.plan_history
    s1 = engine.create_session("tpch")
    s2 = engine.create_session("tpch")
    # concurrent statements check out DIFFERENT pooled executors; both runs
    # must land on ONE store entry (structural fingerprint + node paths)
    errs = []

    def run(sess):
        try:
            engine.execute_sql(AGG_Q, sess)
        except Exception as e:  # pragma: no cover - surfaced by assert below
            errs.append(e)

    threads = [threading.Thread(target=run, args=(s,)) for s in (s1, s2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    payload = engine.last_plan_actuals
    assert payload is not None
    ent = next(e for e in ph.snapshot()
               if e["fingerprint"] == payload["fingerprint"])
    base_execs = ent["executions"]
    assert base_execs >= 2
    # every recorded node path follows the structural "<Op>#<chain>" shape
    for path, rec in ent["nodes"].items():
        op, _, chain = path.partition("#")
        assert rec["op"] == op and chain.startswith("0"), path
        assert rec["actual_rows"] >= 0
    # warm re-execution: executions bump, dispatch counters DON'T change
    engine.execute_sql(AGG_Q, s1)
    warm1 = engine.last_query_counters.snapshot()
    engine.execute_sql(AGG_Q, s1)
    warm2 = engine.last_query_counters.snapshot()
    assert warm2.device_dispatches == warm1.device_dispatches
    assert warm2.host_transfers == warm1.host_transfers
    assert warm2.host_bytes_pulled == warm1.host_bytes_pulled
    ent2 = next(e for e in ph.snapshot()
                if e["fingerprint"] == payload["fingerprint"])
    assert ent2["executions"] == base_execs + 2


def test_misestimating_filter_pins_over_ratio(engine):
    s = engine.create_session("tpch")
    assert len(engine.execute_sql(MIS_Q, s)) == 0  # genuinely empty
    payload = engine.last_plan_actuals
    assert payload is not None
    ent = next(e for e in engine.plan_history.snapshot()
               if e["fingerprint"] == payload["fingerprint"])
    worst = max(r["misestimate_ratio"] for r in ent["nodes"].values())
    assert worst > 1.0
    top = max(ent["nodes"].values(), key=lambda r: r["misestimate_ratio"])
    assert top["direction"] == "over" and top["actual_rows"] == 0
    assert top["est_rows"] and top["est_rows"] > 1
    # the lifetime misestimate counter moved (the /v1/metrics source)
    assert engine.plan_history.misestimates_total >= 1
    assert engine.plan_history.worst_ratio() >= worst or \
        engine.plan_history.worst_ratio() == pytest.approx(worst)


def test_explain_analyze_annotations_and_summary(engine):
    s = engine.create_session("tpch")
    res = engine.execute_sql(f"explain analyze {MIS_Q}", s)
    text = "\n".join(r[0] for r in res.rows())
    assert "[est " in text and "x actual " in text, text
    assert "Misestimates:" in text, text
    # the summary names a structural node path and an over factor
    mis = next(l for l in text.splitlines() if l.startswith("Misestimates:"))
    assert "#" in mis and "over" in mis
    # an on-estimate plan keeps its print free of the summary line
    res2 = engine.execute_sql(
        "explain analyze select count(*) from region", s)
    text2 = "\n".join(r[0] for r in res2.rows())
    assert "Misestimates:" not in text2, text2


def test_system_table_and_event_payload(engine):
    s = engine.create_session("tpch")
    engine.execute_sql(MIS_Q, s)
    from trino_tpu.execution.eventlistener import EventListener

    seen = []

    class L(EventListener):
        def query_completed(self, ev):
            seen.append(ev)

    engine.event_listeners.add(L())
    try:
        rows = engine.execute_sql(
            "select fingerprint, node_path, op, executions, est_rows, "
            "actual_rows, misestimate_ratio, direction "
            "from system.runtime.plan_history", s).rows()
    finally:
        engine.event_listeners.listeners.remove(
            engine.event_listeners.listeners[-1])
    assert rows, "system.runtime.plan_history is empty"
    by_dir = {r[7] for r in rows}
    assert "over" in by_dir
    paths = {r[1] for r in rows}
    assert any(p.startswith("Project#") or p.startswith("Filter#")
               for p in paths), paths
    # the completion event of the system-table query itself carries the
    # per-execution payload (history feeds on EVERY clean local completion)
    ev = seen[-1]
    assert ev.plan_actuals is not None
    assert set(ev.plan_actuals) == {"fingerprint", "nodes"}


def test_history_disabled_store_records_nothing():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    e.plan_history = H.PlanHistoryStore(max_plans=0)
    s = e.create_session("tpch")
    e.execute_sql(AGG_Q, s)
    assert e.plan_history.snapshot() == []
    assert e.last_plan_actuals is None


# ------------------------------------------------------------------ cluster
@pytest.mark.slow
def test_cluster_harvest_merges_with_local_records(tmp_path):
    """Local then in-process-cluster execution of ONE statement: the store
    entry merges both (same structural fingerprint), the fragment roots'
    actuals arrive through the worker harvest / merged-output finals, and
    the cluster result still matches local."""
    from trino_tpu.server.cluster import ClusterCoordinator, WorkerServer

    CATALOGS = {"tpch": {"connector": "tpch", "sf": 0.01,
                         "split_rows": 1 << 11}}
    e = _engine()
    expected = e.execute_sql(AGG_Q).rows()
    payload = e.last_plan_actuals
    assert payload is not None
    ent = next(x for x in e.plan_history.snapshot()
               if x["fingerprint"] == payload["fingerprint"])
    assert ent["executions"] == 1
    local_paths = set(ent["nodes"])
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.3)
    url = coord.start()
    w = None
    try:
        w = WorkerServer(CATALOGS, str(tmp_path / "spool"),
                         coordinator_url=url, node_id="w1")
        w.start()
        coord.wait_for_workers(1, timeout=60)
        assert coord.execute_sql(AGG_Q).rows() == expected
        assert coord.local_fallbacks == 0
    finally:
        coord.stop()
        if w is not None:
            w.stop()
    ent2 = next(x for x in e.plan_history.snapshot()
                if x["fingerprint"] == payload["fingerprint"])
    assert ent2["executions"] == 2
    # the blocking nodes the local run recorded ALL merged a second
    # observation from the cluster run (coordinator finish + worker
    # harvest + fragment finals), on the same structural addresses
    agg_paths = [p for p in local_paths if p.startswith("Aggregate#")]
    assert agg_paths, local_paths
    for p in agg_paths + [p for p in local_paths
                          if p.startswith(("Sort#", "Project#"))]:
        assert ent2["nodes"][p]["executions"] == 2, (p, ent2["nodes"][p])
        assert ent2["nodes"][p]["actual_rows"] == \
            ent["nodes"][p]["actual_rows"]


# ------------------------------------------------------------------ watchdog
def test_second_armed_watchdog_skips_sampling(caplog):
    import logging

    from trino_tpu.execution import tracing

    reg = tracing.InflightRegistry()
    wd1 = tracing.StallWatchdog(registry=reg, stall_s=5.0)
    wd2 = tracing.StallWatchdog(registry=reg, stall_s=5.0)
    try:
        wd1.start()
        assert wd1._thread is not None
        with caplog.at_level(logging.WARNING, logger="trino_tpu.stall"):
            wd2.start()
        assert wd2._thread is None, \
            "second watchdog over the same registry must not sample"
        assert any("already sampled" in r.message for r in caplog.records)
        # verdicts stay live on BOTH (recomputed from the registry)
        assert wd2.verdict()[0] == "ok"
        # a DIFFERENT registry arms independently
        wd3 = tracing.StallWatchdog(registry=tracing.InflightRegistry(),
                                    stall_s=5.0)
        try:
            wd3.start()
            assert wd3._thread is not None
        finally:
            wd3.stop()
    finally:
        wd1.stop()
        wd2.stop()
    # once the owner stopped, the registry is free to arm again
    wd4 = tracing.StallWatchdog(registry=reg, stall_s=5.0)
    try:
        wd4.start()
        assert wd4._thread is not None
    finally:
        wd4.stop()
