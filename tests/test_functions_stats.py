"""Round-5 function families, oracle-tested per family (round-4 verdict item
8): covar_*/regr_*/corr/skewness/kurtosis (reference:
operator/aggregation/CovarianceAggregation, RegressionAggregation,
CentralMomentsAggregation), date_format/format_datetime/date_parse
(DateTimeFunctions), reduce (ArrayReduceFunction), map_from_arrays,
from_unixtime/to_unixtime, and the hash/hex string family."""

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector


@pytest.fixture(scope="module")
def eng():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 12))
    return e


def _xy(eng):
    df = eng.execute_sql(
        "select l_quantity q, l_extendedprice p from lineitem").to_pandas()
    return df["q"].astype(float), df["p"].astype(float)


def test_covariance_family_matches_numpy(eng):
    r = eng.execute_sql(
        """select covar_pop(l_extendedprice, l_quantity) cp,
                  covar_samp(l_extendedprice, l_quantity) cs,
                  corr(l_extendedprice, l_quantity) c,
                  regr_slope(l_extendedprice, l_quantity) sl,
                  regr_intercept(l_extendedprice, l_quantity) ic,
                  regr_count(l_extendedprice, l_quantity) n,
                  regr_avgx(l_extendedprice, l_quantity) ax,
                  regr_avgy(l_extendedprice, l_quantity) ay
           from lineitem""").rows()[0]
    x, y = _xy(eng)
    slope, intercept = np.polyfit(x, y, 1)
    exp = (np.cov(y, x, bias=True)[0, 1], np.cov(y, x, bias=False)[0, 1],
           np.corrcoef(y, x)[0, 1], slope, intercept, len(x),
           x.mean(), y.mean())
    for got, want in zip(r, exp):
        assert abs(float(got) - float(want)) < 1e-6 * max(abs(want), 1), \
            (got, want)


def test_moments_family_matches_numpy(eng):
    r = eng.execute_sql(
        "select skewness(l_quantity) sk, kurtosis(l_quantity) ku, "
        "regr_sxy(l_extendedprice, l_quantity) sxy from lineitem").rows()[0]
    x, y = _xy(eng)
    m = x.mean()
    m2 = ((x - m) ** 2).mean()
    exp_sk = ((x - m) ** 3).mean() / m2 ** 1.5
    exp_ku = ((x - m) ** 4).mean() / m2 ** 2
    exp_sxy = len(x) * np.cov(y, x, bias=True)[0, 1]
    assert abs(float(r[0]) - exp_sk) < 1e-9
    assert abs(float(r[1]) - exp_ku) < 1e-9
    assert abs(float(r[2]) - exp_sxy) < 1e-3 * abs(exp_sxy)


def test_covariance_grouped_and_null_pairs(eng):
    """Grouped stats + pairwise-null semantics: rows where either side is
    NULL must not contribute (reference NULL contract)."""
    rows = eng.execute_sql(
        """select l_returnflag, regr_count(l_extendedprice, l_quantity) n,
                  count(*) c from lineitem
           group by l_returnflag order by l_returnflag""").rows()
    for _, n, c in rows:
        assert n == c  # no NULLs in TPC-H: pairwise count == row count
    one = eng.execute_sql(
        """select covar_samp(x, y) from (
             select cast(null as double) x, 1.0 y
             union all select 2.0, 2.0 union all select 3.0, 4.0)""").rows()
    # only two complete pairs participate
    assert abs(float(one[0][0]) - np.cov([2.0, 3.0], [2.0, 4.0])[0, 1]) < 1e-12


def test_date_format_families(eng):
    rows = eng.execute_sql(
        """select date_format(o_orderdate, '%Y-%m') a,
                  format_datetime(o_orderdate, 'yyyy/MM/dd') b,
                  date_format(o_orderdate, '%W, %e %M %Y') c
           from orders order by o_orderkey limit 1""").rows()
    import datetime

    df = eng.execute_sql(
        "select o_orderdate from orders order by o_orderkey limit 1"
    ).to_pandas()
    d = df.iloc[0, 0]
    d = datetime.date(d.year, d.month, d.day)
    assert rows[0][0] == f"{d.year:04d}-{d.month:02d}"
    assert rows[0][1] == f"{d.year:04d}/{d.month:02d}/{d.day:02d}"
    assert rows[0][2] == d.strftime("%A, ") + str(d.day) \
        + d.strftime(" %B %Y")


def test_date_parse_roundtrip(eng):
    rows = eng.execute_sql(
        """select date_parse(date_format(o_orderdate, '%Y-%m-%d'),
                             '%Y-%m-%d') p, o_orderdate
           from orders order by o_orderkey limit 5""").rows()
    import pandas as pd

    for p, d in rows:
        assert p is not None
        p, d = pd.Timestamp(p), pd.Timestamp(d)
        assert (p.year, p.month, p.day) == (d.year, d.month, d.day), (p, d)


def test_reduce_family(eng):
    r = eng.execute_sql(
        "select reduce(array[1, 2, 3, 4, 5], 0, (s, x) -> s + x) v").rows()
    assert int(r[0][0]) == 15
    r = eng.execute_sql(
        "select reduce(array[3, 1, 4, 1, 5], -1, "
        "(s, x) -> if(x > s, x, s)) v").rows()
    assert int(r[0][0]) == 5
    r = eng.execute_sql(
        "select reduce(array[2, 3], 1, (s, x) -> s * x, s -> s + 100) v"
    ).rows()
    assert int(r[0][0]) == 106
    # empty arrays yield the init value through the masked fold
    r = eng.execute_sql(
        "select reduce(filter(array[1], x -> x > 9), 42, (s, x) -> s + x) v"
    ).rows()
    assert int(r[0][0]) == 42


def test_unixtime_and_hashes(eng):
    r = eng.execute_sql(
        "select to_unixtime(from_unixtime(1700000000.25)) v").rows()
    assert abs(float(r[0][0]) - 1700000000.25) < 1e-3
    import hashlib

    r = eng.execute_sql(
        "select md5(c_mktsegment) m, c_mktsegment s from customer "
        "group by md5(c_mktsegment), c_mktsegment order by s limit 1").rows()
    assert r[0][0] == hashlib.md5(r[0][1].encode()).hexdigest()
    r = eng.execute_sql(
        "select from_hex(to_hex(c_mktsegment)) v, c_mktsegment s "
        "from customer group by from_hex(to_hex(c_mktsegment)), c_mktsegment "
        "limit 1").rows()
    assert r[0][0] == r[0][1]


def test_show_functions_lists_new_families(eng):
    names = {row[0] for row in eng.execute_sql("show functions").rows()}
    for n in ("covar_pop", "corr", "regr_slope", "skewness", "date_format",
              "format_datetime", "date_parse", "reduce", "from_unixtime",
              "sha256"):
        assert n in names, f"{n} missing from SHOW FUNCTIONS"


def test_post_review_hardening(eng):
    # Joda repeated-letter runs render once (EEE = short name, not 3x)
    r = eng.execute_sql(
        "select format_datetime(o_orderdate, 'EEE, dd MMM yyyy') v "
        "from orders order by o_orderkey limit 1").rows()
    import datetime

    d = eng.execute_sql("select o_orderdate from orders "
                        "order by o_orderkey limit 1").to_pandas().iloc[0, 0]
    d = datetime.date(d.year, d.month, d.day)
    assert r[0][0] == d.strftime("%a, ") + f"{d.day:02d}" \
        + d.strftime(" %b %Y"), r
    # date_parse %M = MONTH NAME (the blind-replace bug made it minutes)
    r = eng.execute_sql(
        """select date_parse(date_format(o_orderdate, '%M %d, %Y'),
                             '%M %d, %Y') p, o_orderdate d
           from orders order by o_orderkey limit 3""").rows()
    import pandas as pd

    for p, d2 in rows_iter(r):
        p, d2 = pd.Timestamp(p), pd.Timestamp(d2)
        assert (p.year, p.month, p.day) == (d2.year, d2.month, d2.day)
    # out-of-range date_format -> NULL, not a clamped boundary string
    r = eng.execute_sql(
        "select date_format(date '1899-12-31', '%Y-%m-%d') v").rows()
    assert r[0][0] is None, r
    # regr_r2 of a constant dependent variable = 1.0 (perfect fit)
    r = eng.execute_sql(
        """select regr_r2(y, x) from (
             select 7.0 y, 1.0 x union all select 7.0, 2.0
             union all select 7.0, 5.0)""").rows()
    assert abs(float(r[0][0]) - 1.0) < 1e-12, r


def rows_iter(rows):
    return rows


def test_approx_most_frequent(eng):
    """approx_most_frequent(k, v, cap) -> map(v, bigint) of the top-k value
    counts (reference: ApproximateMostFrequentHistogram; exact counting over
    the key-major sort is within the accuracy contract)."""
    rows = eng.execute_sql(
        """select l_returnflag, approx_most_frequent(3, l_linenumber, 100) m
           from lineitem group by l_returnflag order by l_returnflag""").rows()
    df = eng.execute_sql(
        "select l_returnflag f, l_linenumber n from lineitem").to_pandas()
    for flag, m in rows:
        counts = df[df.f == flag].n.value_counts()
        want = {int(k): int(v) for k, v in counts.head(3).items()}
        assert {int(k): int(v) for k, v in m.items()} == want, flag
    # string-valued global histogram decodes keys through the dictionary
    g = eng.execute_sql(
        "select approx_most_frequent(2, o_orderpriority, 10) m from orders"
    ).rows()[0][0]
    oc = eng.execute_sql(
        "select o_orderpriority p from orders").to_pandas().p.value_counts()
    assert {k: int(v) for k, v in g.items()} \
        == {k: int(v) for k, v in oc.head(2).items()}
    # buckets must be a positive integer constant
    with pytest.raises(Exception, match="buckets"):
        eng.execute_sql(
            "select approx_most_frequent(0, l_linenumber, 9) from lineitem")
