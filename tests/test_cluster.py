"""Multi-process control plane: coordinator + worker PROCESSES over HTTP with
a spooled filesystem exchange (reference test model: DistributedQueryRunner
boots a real coordinator + N workers and runs real exchanges,
testing/trino-testing/.../DistributedQueryRunner.java:108 — here the workers
are genuine OS processes, crossing the same process boundary the reference's
HTTP tasks cross)."""

import json
import os
import pathlib
import subprocess
import sys
import time

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.server.cluster import ClusterCoordinator, WorkerServer

CATALOGS = {"tpch": {"connector": "tpch", "sf": 0.01, "split_rows": 1 << 11}}

Q = """select l_returnflag, l_linestatus, sum(l_quantity) qty, count(*) c
       from lineitem where l_shipdate <= date '1998-09-02'
       group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus"""


def _engine():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    return e


def _spawn_worker(tmp_path, coord_url, node_id):
    env = dict(os.environ)
    env["TRINO_TPU_WORKER_CPU"] = "1"
    repo_root = str(pathlib.Path(__file__).resolve().parents[1])
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "trino_tpu.server.cluster",
         "--coordinator", coord_url, "--catalogs", json.dumps(CATALOGS),
         "--spool", str(tmp_path / "spool"), "--node-id", node_id],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)


@pytest.mark.slow
def test_two_process_cluster(tmp_path):
    """Worker registration + fragment dispatch + spooled exchange across two
    real worker processes; result matches single-process execution."""
    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.3)
    url = coord.start()
    w1 = w2 = None
    try:
        w1 = _spawn_worker(tmp_path, url, "w1")
        w2 = _spawn_worker(tmp_path, url, "w2")
        coord.wait_for_workers(2, timeout=60)
        expected = e.execute_sql(Q).rows()
        got = coord.execute_sql(Q).rows()
        assert got == expected
        nodes = {w.node_id for w in coord.live_workers()}
        assert nodes == {"w1", "w2"}
    finally:
        coord.stop()
        for w in (w1, w2):
            if w is not None:
                w.terminate()
                w.wait(timeout=10)


@pytest.mark.slow
def test_worker_death_reassigns_tasks(tmp_path):
    """Heartbeat failure detection + task reassignment: killing one worker
    mid-cluster must not fail the query (reference: HeartbeatFailureDetector
    gating + FTE task retries on another node)."""
    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.2, max_misses=2)
    url = coord.start()
    w1 = w2 = None
    try:
        w1 = _spawn_worker(tmp_path, url, "w1")
        w2 = _spawn_worker(tmp_path, url, "w2")
        coord.wait_for_workers(2, timeout=60)
        expected = e.execute_sql(Q).rows()
        # kill w2 before dispatch: tasks headed its way must reroute to w1
        w2.kill()
        w2.wait(timeout=10)
        time.sleep(0.6)  # let the failure detector notice
        got = coord.execute_sql(Q).rows()
        assert got == expected
        alive = {w.node_id for w in coord.live_workers()}
        assert alive == {"w1"}
    finally:
        coord.stop()
        for w in (w1, w2):
            if w is not None and w.poll() is None:
                w.terminate()
                w.wait(timeout=10)


def test_task_endpoints_require_hmac(tmp_path):
    """The fragment/task envelope is pickled — an unauthenticated body must be
    rejected BEFORE deserialization (reference: internal-communication shared
    secret).  Signed traffic passes end-to-end."""
    import pickle
    import urllib.error
    import urllib.request

    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.2, secret="s3cret")
    url = coord.start()
    w = WorkerServer(CATALOGS, str(tmp_path / "spool"), coordinator_url=url,
                     node_id="sec", secret="s3cret")
    w.start()
    try:
        coord.wait_for_workers(1, timeout=20)
        blob = pickle.dumps({"fragment_id": "x", "plan": None})
        # unsigned and mis-signed POSTs bounce with 403
        for headers in ({}, {"X-Trino-Internal-Signature": "0" * 64}):
            req = urllib.request.Request(f"{w.url}/v1/fragment", data=blob,
                                         headers=headers)
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=5)
            assert exc.value.code == 403
        # the coordinator signs with the shared secret: full query runs
        assert coord.execute_sql(Q).rows() == e.execute_sql(Q).rows()
    finally:
        w.stop()
        coord.stop()


def test_worker_refuses_unauthenticated_nonloopback(tmp_path, monkeypatch):
    monkeypatch.delenv("TRINO_TPU_CLUSTER_SECRET", raising=False)
    with pytest.raises(ValueError, match="loopback"):
        WorkerServer(CATALOGS, str(tmp_path / "spool"), host="0.0.0.0")


def test_in_process_worker_roundtrip(tmp_path):
    """WorkerServer driven in-process (fast path for CI): announce, dispatch,
    status poll, spooled commit."""
    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.2)
    url = coord.start()
    w = WorkerServer(CATALOGS, str(tmp_path / "spool"), coordinator_url=url,
                     node_id="inproc")
    w.start()
    try:
        coord.wait_for_workers(1, timeout=20)
        expected = e.execute_sql(Q).rows()
        got = coord.execute_sql(Q).rows()
        assert got == expected
    finally:
        w.stop()
        coord.stop()
