"""Multi-process control plane: coordinator + worker PROCESSES over HTTP with
a spooled filesystem exchange (reference test model: DistributedQueryRunner
boots a real coordinator + N workers and runs real exchanges,
testing/trino-testing/.../DistributedQueryRunner.java:108 — here the workers
are genuine OS processes, crossing the same process boundary the reference's
HTTP tasks cross)."""

import json
import os
import pathlib
import subprocess
import sys
import time

import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector
from trino_tpu.server.cluster import ClusterCoordinator, WorkerServer

CATALOGS = {"tpch": {"connector": "tpch", "sf": 0.01, "split_rows": 1 << 11}}

Q = """select l_returnflag, l_linestatus, sum(l_quantity) qty, count(*) c
       from lineitem where l_shipdate <= date '1998-09-02'
       group by l_returnflag, l_linestatus order by l_returnflag, l_linestatus"""


def _engine():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 11))
    return e


def _spawn_worker(tmp_path, coord_url, node_id):
    env = dict(os.environ)
    env["TRINO_TPU_WORKER_CPU"] = "1"
    repo_root = str(pathlib.Path(__file__).resolve().parents[1])
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "trino_tpu.server.cluster",
         "--coordinator", coord_url, "--catalogs", json.dumps(CATALOGS),
         "--spool", str(tmp_path / "spool"), "--node-id", node_id],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL)


@pytest.mark.slow
def test_two_process_cluster(tmp_path):
    """Worker registration + fragment dispatch + spooled exchange across two
    real worker processes; result matches single-process execution."""
    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.3)
    url = coord.start()
    w1 = w2 = None
    try:
        w1 = _spawn_worker(tmp_path, url, "w1")
        w2 = _spawn_worker(tmp_path, url, "w2")
        coord.wait_for_workers(2, timeout=60)
        expected = e.execute_sql(Q).rows()
        got = coord.execute_sql(Q).rows()
        assert got == expected
        nodes = {w.node_id for w in coord.live_workers()}
        assert nodes == {"w1", "w2"}
    finally:
        coord.stop()
        for w in (w1, w2):
            if w is not None:
                w.terminate()
                w.wait(timeout=10)


@pytest.mark.slow
def test_worker_death_reassigns_tasks(tmp_path):
    """Heartbeat failure detection + task reassignment: killing one worker
    mid-cluster must not fail the query (reference: HeartbeatFailureDetector
    gating + FTE task retries on another node)."""
    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.2, max_misses=2)
    url = coord.start()
    w1 = w2 = None
    try:
        w1 = _spawn_worker(tmp_path, url, "w1")
        w2 = _spawn_worker(tmp_path, url, "w2")
        coord.wait_for_workers(2, timeout=60)
        expected = e.execute_sql(Q).rows()
        # kill w2 before dispatch: tasks headed its way must reroute to w1
        w2.kill()
        w2.wait(timeout=10)
        time.sleep(0.6)  # let the failure detector notice
        got = coord.execute_sql(Q).rows()
        assert got == expected
        alive = {w.node_id for w in coord.live_workers()}
        assert alive == {"w1"}
    finally:
        coord.stop()
        for w in (w1, w2):
            if w is not None and w.poll() is None:
                w.terminate()
                w.wait(timeout=10)


Q3 = """select l_orderkey, sum(l_extendedprice * (1 - l_discount)) revenue,
               o_orderdate, o_shippriority
        from customer, orders, lineitem
        where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
          and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
          and l_shipdate > date '1995-03-15'
        group by l_orderkey, o_orderdate, o_shippriority
        order by revenue desc, o_orderdate limit 10"""

Q9 = """select nation, o_year, sum(amount) as sum_profit from (
          select n_name as nation, extract(year from o_orderdate) as o_year,
            l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity as amount
          from part, supplier, lineitem, partsupp, orders, nation
          where s_suppkey = l_suppkey and ps_suppkey = l_suppkey
            and ps_partkey = l_partkey and p_partkey = l_partkey
            and o_orderkey = l_orderkey and s_nationkey = n_nationkey
            and p_name like '%green%') as profit
        group by nation, o_year order by nation, o_year desc"""


@pytest.mark.slow
def test_cluster_join_queries_across_processes(tmp_path):
    """Q3 and Q9 run END-TO-END through the cluster plane across two real
    worker processes: join fragments fan out by probe splits, aggregates
    consume spooled join output, the remainder finishes on the coordinator
    (round-2 VERDICT #2 done-criterion)."""
    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.3)
    url = coord.start()
    w1 = w2 = None
    try:
        w1 = _spawn_worker(tmp_path, url, "w1")
        w2 = _spawn_worker(tmp_path, url, "w2")
        coord.wait_for_workers(2, timeout=60)
        for q in (Q3, Q9):
            expected = e.execute_sql(q).rows()
            got = coord.execute_sql(q).rows()
            assert got == expected
    finally:
        coord.stop()
        for w in (w1, w2):
            if w is not None:
                w.terminate()
                w.wait(timeout=10)


@pytest.mark.slow
def test_cluster_mid_query_worker_kill(tmp_path):
    """A worker dies MID-QUERY while running join-fragment tasks: the
    coordinator reassigns its tasks to the survivor and the result still
    matches local (round-2 VERDICT #2 done-criterion)."""
    import threading

    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.2, max_misses=2,
                               task_timeout=30.0)
    url = coord.start()
    w1 = w2 = None
    try:
        w1 = _spawn_worker(tmp_path, url, "w1")
        w2 = _spawn_worker(tmp_path, url, "w2")
        coord.wait_for_workers(2, timeout=60)
        expected = e.execute_sql(Q3).rows()
        result: dict = {}

        def run():
            try:
                result["rows"] = coord.execute_sql(Q3).rows()
            except Exception as ex:  # pragma: no cover - surfaced in assert
                result["error"] = ex

        t = threading.Thread(target=run)
        t.start()
        time.sleep(1.0)  # let dispatch begin (workers are mid-fragment)
        w2.kill()
        w2.wait(timeout=10)
        t.join(timeout=300)
        assert not t.is_alive(), "query wedged after worker death"
        assert "error" not in result, result.get("error")
        assert result["rows"] == expected
    finally:
        coord.stop()
        for w in (w1, w2):
            if w is not None and w.poll() is None:
                w.terminate()
                w.wait(timeout=10)


def test_task_endpoints_require_hmac(tmp_path):
    """The fragment/task envelope is pickled — an unauthenticated body must be
    rejected BEFORE deserialization (reference: internal-communication shared
    secret).  Signed traffic passes end-to-end."""
    import pickle
    import urllib.error
    import urllib.request

    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.2, secret="s3cret")
    url = coord.start()
    w = WorkerServer(CATALOGS, str(tmp_path / "spool"), coordinator_url=url,
                     node_id="sec", secret="s3cret")
    w.start()
    try:
        coord.wait_for_workers(1, timeout=60)
        blob = pickle.dumps({"fragment_id": "x", "plan": None})
        # unsigned and mis-signed POSTs bounce with 403
        for headers in ({}, {"X-Trino-Internal-Signature": "0" * 64}):
            req = urllib.request.Request(f"{w.url}/v1/fragment", data=blob,
                                         headers=headers)
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=5)
            assert exc.value.code == 403
        # the coordinator signs with the shared secret: full query runs
        assert coord.execute_sql(Q).rows() == e.execute_sql(Q).rows()
    finally:
        w.stop()
        coord.stop()


def test_worker_refuses_unauthenticated_nonloopback(tmp_path, monkeypatch):
    monkeypatch.delenv("TRINO_TPU_CLUSTER_SECRET", raising=False)
    with pytest.raises(ValueError, match="loopback"):
        WorkerServer(CATALOGS, str(tmp_path / "spool"), host="0.0.0.0")


def test_in_process_worker_roundtrip(tmp_path):
    """WorkerServer driven in-process (fast path for CI): announce, dispatch,
    status poll, spooled commit."""
    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.2)
    url = coord.start()
    w = WorkerServer(CATALOGS, str(tmp_path / "spool"), coordinator_url=url,
                     node_id="inproc")
    w.start()
    try:
        coord.wait_for_workers(1, timeout=60)
        expected = e.execute_sql(Q).rows()
        got = coord.execute_sql(Q).rows()
        assert got == expected
    finally:
        w.stop()
        coord.stop()


def test_distributed_query_merges_worker_counters(tmp_path):
    """Round-7 acceptance: a distributed run reports MERGED coordinator +
    worker device-boundary counters.  Worker tasks record their own
    QueryCounters, ship them on the task status response, and the coordinator
    folds every harvested snapshot (plus its own local spend) into
    last_query_counters and the engine totals — so distributed queries are no
    longer invisible to the budget surfaces."""
    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.2)
    url = coord.start()
    w = WorkerServer(CATALOGS, str(tmp_path / "spool"), coordinator_url=url,
                     node_id="inproc")
    w.start()
    try:
        coord.wait_for_workers(1, timeout=60)
        expected = e.execute_sql(Q9).rows()
        before = e.counters_total.device_dispatches
        got = coord.execute_sql(Q9).rows()
        assert got == expected
        assert coord.local_fallbacks == 0, coord.last_fallback_error
        merged = coord.last_query_counters
        workers = coord._qc_workers
        # the worker half actually arrived (not just coordinator-local spend)
        assert workers.device_dispatches > 0, "no worker counters harvested"
        assert workers.host_bytes_pulled > 0
        # merged totals = coordinator-local + harvested worker snapshots
        # (the merge is constructed that way; assert both halves are present
        # and the engine totals advanced by the merged amount)
        assert merged.device_dispatches >= workers.device_dispatches
        assert e.counters_total.device_dispatches - before \
            == merged.device_dispatches
        # worker sites flow through the merge with their fte/stream tags
        assert any(k.startswith(("fte.", "step", "dist."))
                   or "/" in k for k in merged.sites), merged.sites
        # worker span trees ride back too (task root + dispatch children)
        names = {s["name"] for s in coord.last_query_worker_spans}
        assert "task" in names and "dispatch" in names, names
        # per-site sums still equal the merged totals after the cluster merge
        assert sum(v["dispatches"] for v in merged.sites.values()) \
            == merged.device_dispatches
    finally:
        w.stop()
        coord.stop()


def test_stalled_worker_marked_degraded_and_unscheduled(tmp_path):
    """Round-8 acceptance: a worker whose stall watchdog reports a wedged
    in-flight dispatch keeps answering HTTP (alive, harvestable, streams
    drain/retry as before — the speculation and stream-RETRY paths covered
    by the other tests in this module are untouched) but is marked DEGRADED:
    the coordinator stops scheduling new tasks to it, the query completes
    entirely on the healthy worker, and scheduling resumes once the stall
    clears."""
    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.1)
    url = coord.start()
    # realistic threshold: a genuine cold compile on this box takes seconds
    # and must NOT read as a stall; the wedge below is injected as an entry
    # aged far past it (the same record a _jit stuck on a dead tunnel holds)
    wa = WorkerServer(CATALOGS, str(tmp_path / "spool"), coordinator_url=url,
                      node_id="wa", stall_s=30.0)
    wb = WorkerServer(CATALOGS, str(tmp_path / "spool"), coordinator_url=url,
                      node_id="wb", stall_s=30.0)
    wa.start()
    wb.start()
    try:
        coord.wait_for_workers(2, timeout=60)
        expected = e.execute_sql(Q).rows()
        # wedge wa: an in-flight dispatch entry an hour old on ITS registry
        tok = wa.inflight.enter("dispatch", site="probe.step")
        wa.inflight._entries[tok].start_monotonic -= 3600.0
        deadline = time.time() + 30
        while time.time() < deadline:
            with coord._lock:
                w = coord.workers.get("wa")
                if w is not None and w.degraded:
                    break
            time.sleep(0.05)
        with coord._lock:
            assert coord.workers["wa"].degraded, "wa never marked degraded"
            assert coord.workers["wa"].alive, "degraded != dead"
            assert coord.workers["wa"].health == "stalled"
        assert {w.node_id for w in coord.live_workers()} == {"wb"}
        # the query schedules ONLY onto the healthy worker and still succeeds
        got = coord.execute_sql(Q).rows()
        assert got == expected
        assert coord.local_fallbacks == 0, coord.last_fallback_error
        assert not wa.tasks, f"degraded worker received tasks: {list(wa.tasks)}"
        assert wb.tasks, "healthy worker ran nothing"
        # stall clears -> verdict recovers -> wa returns to scheduling
        wa.inflight.exit(tok)
        deadline = time.time() + 30
        while time.time() < deadline:
            with coord._lock:
                if not coord.workers["wa"].degraded:
                    break
            time.sleep(0.05)
        assert {w.node_id for w in coord.live_workers()} == {"wa", "wb"}
    finally:
        wa.stop()
        wb.stop()
        coord.stop()


def test_speculative_execution_of_stragglers(tmp_path):
    """Once every task is dispatched, a straggler re-dispatches to another
    worker; first-commit-wins dedup makes the duplicate harmless and the
    query finishes at the fast worker's pace (reference: the FTE scheduler's
    SPECULATIVE task class, TaskExecutionClass.java)."""
    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.2,
                               speculative_factor=2.0, task_timeout=60.0)
    url = coord.start()
    w1 = WorkerServer(CATALOGS, str(tmp_path / "spool"), coordinator_url=url,
                      node_id="fast")
    w2 = WorkerServer(CATALOGS, str(tmp_path / "spool"), coordinator_url=url,
                      node_id="slow")
    w1.start()
    w2.start()
    try:
        coord.wait_for_workers(2, timeout=30)
        expected = e.execute_sql(Q).rows()
        coord.execute_sql(Q)  # warm both workers' compile caches
        # 20s straggler cost: big enough that "the query finished in well
        # under one straggler" stays unambiguous on a loaded 1-core box
        # (wall-clock margins below that were flaky under background load)
        orig = w2.local._agg_compiled
        w2.local._agg_compiled = lambda node, _o=orig: (time.sleep(20),
                                                        _o(node))[1]
        t0 = time.time()
        got = coord.execute_sql(Q).rows()
        elapsed = time.time() - t0
        assert got == expected
        assert coord.speculative_tasks >= 1, "no speculation happened"
        assert elapsed < 19.0, \
            f"query waited out the straggler ({elapsed:.1f}s)"
    finally:
        w1.stop()
        w2.stop()
        coord.stop()


def test_fte_memory_failure_bisects_task(tmp_path):
    """A device-memory failure inside a partial-aggregation task bisects its
    split set and merges the halves (the memory-growth retry analog:
    ExponentialGrowthPartitionMemoryEstimator)."""
    from trino_tpu.exec import fte as F

    e = _engine()
    s = e.create_session("tpch")
    from trino_tpu.sql.frontend import compile_sql

    plan = compile_sql(Q, e, s)
    expected = e.execute_sql(Q, s).rows()
    ex = F.FaultTolerantExecutor(e.catalogs, str(tmp_path / "spool"))
    calls = []
    orig = F._partial_once

    def flaky(node, stream, key_types, acc_specs, step, splits, tick=None):
        calls.append(len(splits))
        if len(splits) > 1:
            raise MemoryError("synthetic RESOURCE_EXHAUSTED")
        return orig(node, stream, key_types, acc_specs, step, splits, tick)

    F._partial_once = flaky
    try:
        got = ex.execute(plan).rows()
    finally:
        F._partial_once = orig
    assert got == expected
    assert any(c > 1 for c in calls) and any(c == 1 for c in calls), \
        "bisection never recursed"


def test_graceful_shutdown_drains_and_leaves(tmp_path):
    """Graceful shutdown (reference: GracefulShutdownHandler): the worker
    finishes running tasks, refuses new ones with 503, reports
    shutting_down, and leaves the cluster; queries keep succeeding on the
    remaining worker."""
    import urllib.request

    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.1)
    url = coord.start()
    w1 = WorkerServer(CATALOGS, str(tmp_path / "spool"), coordinator_url=url,
                      node_id="w1", announce_interval=0.1)
    w2 = WorkerServer(CATALOGS, str(tmp_path / "spool"), coordinator_url=url,
                      node_id="w2", announce_interval=0.1)
    w1.start()
    w2.start()
    try:
        coord.wait_for_workers(2, timeout=60)
        expected = e.execute_sql(Q).rows()
        assert coord.execute_sql(Q).rows() == expected

        w1.shutdown_gracefully()
        try:
            info = json.loads(urllib.request.urlopen(
                f"{w1.url}/v1/info", timeout=5).read())
            assert info["state"] == "shutting_down"
        except urllib.error.HTTPError:
            raise  # a BROKEN info endpoint must not pass
        except (OSError, urllib.error.URLError):
            pass  # drain was idle-fast: the server already exited — the
            # coordinator-side assertions below are the real contract
        # the coordinator drains w1 out of scheduling within an announce tick
        deadline = time.time() + 30
        while time.time() < deadline:
            live = {w.node_id for w in coord.live_workers()}
            if live == {"w2"}:
                break
            time.sleep(0.05)
        assert {w.node_id for w in coord.live_workers()} == {"w2"}
        # queries still work on the remaining worker
        assert coord.execute_sql(Q).rows() == expected
        # the drained worker eventually leaves entirely (announce "gone")
        deadline = time.time() + 30
        while time.time() < deadline:
            with coord._lock:
                if "w1" not in coord.workers:
                    break
            time.sleep(0.05)
        with coord._lock:
            assert "w1" not in coord.workers
    finally:
        w2.stop()
        coord.stop()


def test_task_admission_backpressure(tmp_path):
    """A worker at max_concurrent_tasks refuses with 429; the coordinator
    re-offers instead of burning retry attempts, and the query completes."""
    e = _engine()
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.2, splits_per_task=1,
                               max_attempts=2)
    url = coord.start()
    w = WorkerServer(CATALOGS, str(tmp_path / "spool"), coordinator_url=url,
                     node_id="slow", announce_interval=0.1)
    w.max_concurrent_tasks = 1  # every concurrent dispatch beyond 1 -> 429
    w.start()
    try:
        coord.wait_for_workers(1, timeout=60)
        expected = e.execute_sql(Q).rows()
        assert coord.execute_sql(Q).rows() == expected
    finally:
        w.stop()
        coord.stop()


@pytest.mark.slow
def test_cluster_tpcds_star(tmp_path):
    """The OS-process control plane schedules a TPC-DS star query: worker
    build_catalogs instantiates the TPC-DS connector, split tasks fan out
    over store_sales, and the coordinator merges partials (round 4: the
    cluster plane is no longer TPC-H-only)."""
    from trino_tpu.connectors.tpcds import TpcdsConnector

    cats = {"tpcds": {"connector": "tpcds", "sf": 0.01,
                      "split_rows": 1 << 12}}
    e = Engine()
    e.register_catalog("tpcds", TpcdsConnector(sf=0.01, split_rows=1 << 12))
    coord = ClusterCoordinator(e, str(tmp_path / "spool"),
                               heartbeat_interval=0.3)
    url = coord.start()
    w1 = w2 = None
    sql = ("select i_category, sum(ss_ext_sales_price) rev, count(*) c "
           "from store_sales, item, date_dim "
           "where ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk "
           "and d_year = 2000 group by i_category "
           "order by rev desc, i_category")
    try:
        env = dict(os.environ)
        env["TRINO_TPU_WORKER_CPU"] = "1"
        repo_root = str(pathlib.Path(__file__).resolve().parents[1])
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        procs = []
        for nid in ("dsw1", "dsw2"):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "trino_tpu.server.cluster",
                 "--coordinator", url, "--catalogs", json.dumps(cats),
                 "--spool", str(tmp_path / "spool"), "--node-id", nid],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL))
        w1, w2 = procs
        coord.wait_for_workers(2, timeout=60)
        expected = e.execute_sql(sql).rows()
        got = coord.execute_sql(sql).rows()
        assert got == expected and len(got) > 3
    finally:
        coord.stop()
        for w in (w1, w2):
            if w is not None:
                w.terminate()
                w.wait(timeout=10)
