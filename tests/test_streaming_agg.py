"""Streaming (sorted-input) aggregation (reference: the streaming aggregation
operator over pre-grouped input, operator/aggregation/): when the scan's
declared sort order makes group rows contiguous, segmented reduces replace
the hash probe loop."""

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector


@pytest.fixture()
def seng(monkeypatch):
    """Engine at a scale where the composite partsupp key exceeds the
    direct-index range (30 bits > 24), so sorted multi-key GROUP BYs take the
    streaming path; a counter asserts it actually runs."""
    import trino_tpu.exec.local_executor as LE

    calls = {"n": 0}
    orig = LE.LocalExecutor._run_streaming_aggregate

    def counting(self, *a, **k):
        calls["n"] += 1
        return orig(self, *a, **k)

    monkeypatch.setattr(LE.LocalExecutor, "_run_streaming_aggregate", counting)
    # the scan-fused whole-pipeline path outranks streaming aggregation for
    # traced sources at this scale; disable it so these tests keep exercising
    # the streaming machinery (its scaling niche: group counts beyond the
    # fused path's table ceiling)
    monkeypatch.setattr(LE.LocalExecutor, "_run_aggregate_scan_fused",
                        lambda self, *a, **k: None)
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.5, split_rows=1 << 17))
    return e, e.create_session("tpch"), calls


def _oracle(sql):
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.5, split_rows=1 << 17))
    s = e.create_session("tpch")
    import trino_tpu.exec.local_executor as LE

    orig = LE.LocalExecutor._streaming_agg_order
    LE.LocalExecutor._streaming_agg_order = lambda self, st, nd: None
    try:
        return e.execute_sql(sql, s).to_pandas()
    finally:
        LE.LocalExecutor._streaming_agg_order = orig


def test_sorted_multikey_aggregation_streams(seng):
    e, s, calls = seng
    sql = ("select ps_suppkey, ps_partkey, sum(ps_supplycost) sc, count(*) c, "
           "min(ps_availqty) mn, max(ps_availqty) mx, avg(ps_supplycost) av "
           "from partsupp group by ps_suppkey, ps_partkey "
           "order by ps_partkey, ps_suppkey limit 15")
    got = e.execute_sql(sql, s).to_pandas()
    assert calls["n"] == 1, "streaming path did not activate"
    exp = _oracle(sql)
    assert got.values.tolist() == exp.values.tolist()


def test_streaming_agg_with_filter_masked_lanes(seng):
    e, s, calls = seng
    sql = ("select ps_partkey, ps_suppkey, sum(ps_supplycost) sc "
           "from partsupp where ps_availqty > 5000 "
           "group by ps_partkey, ps_suppkey "
           "order by sc desc, ps_partkey limit 10")
    got = e.execute_sql(sql, s).to_pandas()
    assert calls["n"] == 1
    exp = _oracle(sql)
    assert got.values.tolist() == exp.values.tolist()


def test_unsorted_keys_do_not_stream(seng):
    e, s, calls = seng
    # ps_suppkey alone is NOT a sort-order prefix: must not stream
    e.execute_sql("select ps_suppkey, count(*) c from partsupp "
                  "group by ps_suppkey order by ps_suppkey limit 5", s)
    assert calls["n"] == 0


def test_streaming_agg_overflow_grows_and_restreams(seng):
    """An undersized merge table overflows, grows 4x, and re-streams the
    input; results stay exact (covers the grow path's reservation deltas and
    pages() replayability)."""
    e, s, calls = seng
    e.execute_sql("set session group_by_capacity = 64", s)
    sql = ("select ps_partkey, ps_suppkey, sum(ps_availqty) q from partsupp "
           "where ps_partkey <= 2000 group by ps_partkey, ps_suppkey "
           "order by ps_partkey, ps_suppkey limit 20")
    got = e.execute_sql(sql, s).to_pandas()
    assert calls["n"] == 1
    exp = _oracle(sql)
    assert got.values.tolist() == exp.values.tolist()
