"""TPC-DS query breadth, round 4 (VERDICT r3 item 7): multi-channel unions,
ROLLUP reports, time/household-demographic stars, and ship-lag bucket reports
vs pandas oracles.  Reference corpus: testing/trino-benchmark-queries/ +
plugin/trino-tpcds query suite."""

import numpy as np
import pandas as pd
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpcds import TpcdsConnector

SF = 0.01


@pytest.fixture(scope="module")
def eng():
    e = Engine()
    e.register_catalog("tpcds", TpcdsConnector(sf=SF, split_rows=1 << 14))
    return e, e.create_session("tpcds")


def _table(conn, t, names):
    dicts = conn.dictionaries(t)
    cols = {}
    for name in names:
        parts = []
        for sp in conn.splits(t):
            pg = conn.generate(sp, [name])
            a = np.asarray(pg.column(name))
            if pg.valid is not None:
                a = a[np.asarray(pg.valid_mask())]
            parts.append(a)
        arr = np.concatenate(parts)
        if dicts.get(name) is not None:
            arr = dicts[name].decode(arr)
        f = conn.schema(t).field(name)
        from trino_tpu.types import DecimalType

        if isinstance(f.type, DecimalType):
            arr = arr.astype(np.float64) / (10 ** f.type.scale)
        cols[name] = arr
    return pd.DataFrame(cols)


@pytest.fixture(scope="module")
def host(eng):
    e, _ = eng
    conn = e.catalogs["tpcds"]
    return {
        "store_sales": _table(conn, "store_sales", [
            "ss_sold_date_sk", "ss_sold_time_sk", "ss_item_sk", "ss_store_sk",
            "ss_hdemo_sk", "ss_customer_sk", "ss_ticket_number",
            "ss_ext_sales_price", "ss_net_profit", "ss_quantity",
            "ss_sales_price"]),
        "catalog_sales": _table(conn, "catalog_sales", [
            "cs_sold_date_sk", "cs_ship_date_sk", "cs_item_sk",
            "cs_call_center_sk", "cs_warehouse_sk", "cs_ship_mode_sk",
            "cs_bill_cdemo_sk", "cs_net_profit", "cs_ext_sales_price",
            "cs_quantity", "cs_list_price", "cs_coupon_amt"]),
        "web_sales": _table(conn, "web_sales", [
            "ws_sold_date_sk", "ws_item_sk", "ws_web_site_sk",
            "ws_net_profit", "ws_ext_sales_price"]),
        "date_dim": _table(conn, "date_dim", [
            "d_date_sk", "d_year", "d_moy", "d_dow", "d_day_name"]),
        "item": _table(conn, "item", [
            "i_item_sk", "i_item_id", "i_brand_id", "i_brand", "i_manufact_id",
            "i_category", "i_manager_id"]),
        "time_dim": _table(conn, "time_dim", [
            "t_time_sk", "t_hour", "t_minute"]),
        "household_demographics": _table(conn, "household_demographics", [
            "hd_demo_sk", "hd_dep_count", "hd_vehicle_count"]),
        "store": _table(conn, "store", [
            "s_store_sk", "s_store_name", "s_store_id"]),
        "warehouse": _table(conn, "warehouse", [
            "w_warehouse_sk", "w_warehouse_name"]),
        "ship_mode": _table(conn, "ship_mode", [
            "sm_ship_mode_sk", "sm_type"]),
        "call_center": _table(conn, "call_center", [
            "cc_call_center_sk", "cc_name"]),
        "customer_demographics": _table(conn, "customer_demographics", [
            "cd_demo_sk", "cd_gender", "cd_education_status"]),
    }


def _check(got, ref, float_cols, rtol=1e-9):
    assert len(got) == len(ref), (len(got), len(ref))
    for c in got.columns:
        a, b = got[c].to_numpy(), ref[c].to_numpy()
        if c in float_cols:
            np.testing.assert_allclose(a.astype(float), b.astype(float),
                                       rtol=rtol, err_msg=c)
        else:
            assert list(a) == list(b), c


def test_q52_brand_revenue_november(eng, host):
    e, s = eng
    got = e.execute_sql(
        "select d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) rev "
        "from date_dim, store_sales, item "
        "where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk "
        "and i_manager_id = 1 and d_moy = 11 and d_year = 2000 "
        "group by d_year, i_brand_id, i_brand "
        "order by d_year, rev desc, i_brand_id limit 100", s).to_pandas()
    ss, dd, it = host["store_sales"], host["date_dim"], host["item"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk") \
        .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j = j[(j.i_manager_id == 1) & (j.d_moy == 11) & (j.d_year == 2000)]
    ref = j.groupby(["d_year", "i_brand_id", "i_brand"], as_index=False) \
        .ss_ext_sales_price.sum() \
        .rename(columns={"ss_ext_sales_price": "rev"}) \
        .sort_values(["d_year", "rev", "i_brand_id"],
                     ascending=[True, False, True]).head(100)
    _check(got, ref[["d_year", "i_brand_id", "i_brand", "rev"]], {"rev"})


def test_q43_store_sales_by_day_name(eng, host):
    e, s = eng
    got = e.execute_sql(
        "select s_store_name, s_store_id, "
        "sum(case when d_day_name = 'Sunday' then ss_sales_price else 0 end) sun_sales, "
        "sum(case when d_day_name = 'Monday' then ss_sales_price else 0 end) mon_sales, "
        "sum(case when d_day_name = 'Friday' then ss_sales_price else 0 end) fri_sales "
        "from date_dim, store_sales, store "
        "where d_date_sk = ss_sold_date_sk and s_store_sk = ss_store_sk "
        "and d_year = 2001 "
        "group by s_store_name, s_store_id "
        "order by s_store_name, s_store_id limit 100", s).to_pandas()
    ss, dd, st = host["store_sales"], host["date_dim"], host["store"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk") \
        .merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    j = j[j.d_year == 2001]
    for day, col in (("Sunday", "sun_sales"), ("Monday", "mon_sales"),
                     ("Friday", "fri_sales")):
        j[col] = np.where(j.d_day_name == day, j.ss_sales_price, 0.0)
    ref = j.groupby(["s_store_name", "s_store_id"], as_index=False)[
        ["sun_sales", "mon_sales", "fri_sales"]].sum() \
        .sort_values(["s_store_name", "s_store_id"]).head(100)
    _check(got, ref, {"sun_sales", "mon_sales", "fri_sales"})


def test_q96_evening_shoppers(eng, host):
    e, s = eng
    got = e.execute_sql(
        "select count(*) cnt from store_sales, household_demographics, "
        "time_dim, store "
        "where ss_sold_time_sk = t_time_sk "
        "and ss_hdemo_sk = hd_demo_sk and ss_store_sk = s_store_sk "
        "and t_hour = 20 and t_minute >= 30 and hd_dep_count = 7",
        s).to_pandas()
    ss, hd, td = (host["store_sales"], host["household_demographics"],
                  host["time_dim"])
    st = host["store"]
    j = ss.merge(td, left_on="ss_sold_time_sk", right_on="t_time_sk") \
        .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk") \
        .merge(st, left_on="ss_store_sk", right_on="s_store_sk")
    n = len(j[(j.t_hour == 20) & (j.t_minute >= 30) & (j.hd_dep_count == 7)])
    assert int(got["cnt"].iloc[0]) == n


def test_q99_ship_lag_buckets(eng, host):
    e, s = eng
    got = e.execute_sql(
        "select w_warehouse_name, sm_type, cc_name, "
        "sum(case when cs_ship_date_sk - cs_sold_date_sk <= 30 "
        "then 1 else 0 end) d30, "
        "sum(case when cs_ship_date_sk - cs_sold_date_sk > 30 "
        "and cs_ship_date_sk - cs_sold_date_sk <= 60 then 1 else 0 end) d60, "
        "sum(case when cs_ship_date_sk - cs_sold_date_sk > 60 "
        "then 1 else 0 end) dmore "
        "from catalog_sales, warehouse, ship_mode, call_center "
        "where cs_warehouse_sk = w_warehouse_sk "
        "and cs_ship_mode_sk = sm_ship_mode_sk "
        "and cs_call_center_sk = cc_call_center_sk "
        "group by w_warehouse_name, sm_type, cc_name "
        "order by w_warehouse_name, sm_type, cc_name limit 100",
        s).to_pandas()
    cs, w, sm, cc = (host["catalog_sales"], host["warehouse"],
                     host["ship_mode"], host["call_center"])
    j = cs.merge(w, left_on="cs_warehouse_sk", right_on="w_warehouse_sk") \
        .merge(sm, left_on="cs_ship_mode_sk", right_on="sm_ship_mode_sk") \
        .merge(cc, left_on="cs_call_center_sk", right_on="cc_call_center_sk")
    lag = j.cs_ship_date_sk - j.cs_sold_date_sk
    j["d30"] = (lag <= 30).astype(int)
    j["d60"] = ((lag > 30) & (lag <= 60)).astype(int)
    j["dmore"] = (lag > 60).astype(int)
    ref = j.groupby(["w_warehouse_name", "sm_type", "cc_name"],
                    as_index=False)[["d30", "d60", "dmore"]].sum() \
        .sort_values(["w_warehouse_name", "sm_type", "cc_name"]).head(100)
    _check(got, ref, {"d30", "d60", "dmore"})


def test_q77_multichannel_profit_rollup(eng, host):
    """The Q77-family shape: per-channel profit union-ALL'd, then a ROLLUP
    report over (channel, id) — multi-channel union + ROLLUP in one query."""
    e, s = eng
    got = e.execute_sql(
        "select channel, id, sum(profit) profit from ("
        "  select 1 as channel, ss_store_sk as id, ss_net_profit as profit "
        "  from store_sales "
        "  union all "
        "  select 2 as channel, cs_call_center_sk as id, cs_net_profit "
        "  from catalog_sales "
        "  union all "
        "  select 3 as channel, ws_web_site_sk as id, ws_net_profit "
        "  from web_sales) x "
        "group by rollup (channel, id) "
        "order by channel, id limit 200", s).to_pandas()
    ss, cs, ws = host["store_sales"], host["catalog_sales"], host["web_sales"]
    u = pd.concat([
        pd.DataFrame({"channel": 1, "id": ss.ss_store_sk,
                      "profit": ss.ss_net_profit}),
        pd.DataFrame({"channel": 2, "id": cs.cs_call_center_sk,
                      "profit": cs.cs_net_profit}),
        pd.DataFrame({"channel": 3, "id": ws.ws_web_site_sk,
                      "profit": ws.ws_net_profit}),
    ], ignore_index=True)
    lvl2 = u.groupby(["channel", "id"], as_index=False).profit.sum()
    lvl1 = u.groupby(["channel"], as_index=False).profit.sum()
    lvl1["id"] = np.nan
    total = pd.DataFrame({"channel": [np.nan], "id": [np.nan],
                          "profit": [u.profit.sum()]})
    ref = pd.concat([lvl2, lvl1, total], ignore_index=True)
    # engine ORDER BY: nulls last per key — emulate with +inf sentinels
    ref = ref.sort_values(["channel", "id"],
                          key=lambda c: c.fillna(np.inf)).head(200)
    assert len(got) == len(ref)
    ga = got.fillna(-1).to_numpy(dtype=float)
    rb = ref.fillna(-1)[["channel", "id", "profit"]].to_numpy(dtype=float)
    np.testing.assert_allclose(ga[:, :2], rb[:, :2])
    np.testing.assert_allclose(ga[:, 2], rb[:, 2], rtol=1e-9)


def test_q33_multichannel_manufact_revenue(eng, host):
    e, s = eng
    got = e.execute_sql(
        "select i_manufact_id, sum(total_sales) total_sales from ("
        "  select i_manufact_id, sum(ss_ext_sales_price) total_sales "
        "  from store_sales, date_dim, item "
        "  where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk "
        "  and d_year = 1999 and d_moy = 3 group by i_manufact_id "
        "  union all "
        "  select i_manufact_id, sum(cs_ext_sales_price) total_sales "
        "  from catalog_sales, date_dim, item "
        "  where cs_sold_date_sk = d_date_sk and cs_item_sk = i_item_sk "
        "  and d_year = 1999 and d_moy = 3 group by i_manufact_id "
        "  union all "
        "  select i_manufact_id, sum(ws_ext_sales_price) total_sales "
        "  from web_sales, date_dim, item "
        "  where ws_sold_date_sk = d_date_sk and ws_item_sk = i_item_sk "
        "  and d_year = 1999 and d_moy = 3 group by i_manufact_id) x "
        "group by i_manufact_id order by total_sales desc, i_manufact_id "
        "limit 50", s).to_pandas()
    dd, it = host["date_dim"], host["item"]
    frames = []
    for t, dk, ik, v in (("store_sales", "ss_sold_date_sk", "ss_item_sk",
                          "ss_ext_sales_price"),
                         ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
                          "cs_ext_sales_price"),
                         ("web_sales", "ws_sold_date_sk", "ws_item_sk",
                          "ws_ext_sales_price")):
        j = host[t].merge(dd, left_on=dk, right_on="d_date_sk") \
            .merge(it, left_on=ik, right_on="i_item_sk")
        j = j[(j.d_year == 1999) & (j.d_moy == 3)]
        frames.append(j.groupby("i_manufact_id", as_index=False)[v].sum()
                      .rename(columns={v: "total_sales"}))
    u = pd.concat(frames, ignore_index=True)
    ref = u.groupby("i_manufact_id", as_index=False).total_sales.sum() \
        .sort_values(["total_sales", "i_manufact_id"],
                     ascending=[False, True]).head(50)
    _check(got, ref[["i_manufact_id", "total_sales"]], {"total_sales"})


def test_q18_catalog_rollup_averages(eng, host):
    e, s = eng
    got = e.execute_sql(
        "select i_item_id, avg(cs_quantity) agg1, avg(cs_list_price) agg2, "
        "avg(cs_coupon_amt) agg3 "
        "from catalog_sales, customer_demographics, date_dim, item "
        "where cs_sold_date_sk = d_date_sk and cs_item_sk = i_item_sk "
        "and cs_bill_cdemo_sk = cd_demo_sk and cd_gender = 'F' "
        "and cd_education_status = 'College' and d_year = 1998 "
        "group by rollup (i_item_id) order by i_item_id limit 100",
        s).to_pandas()
    cs, cd = host["catalog_sales"], host["customer_demographics"]
    dd, it = host["date_dim"], host["item"]
    j = cs.merge(dd, left_on="cs_sold_date_sk", right_on="d_date_sk") \
        .merge(it, left_on="cs_item_sk", right_on="i_item_sk") \
        .merge(cd, left_on="cs_bill_cdemo_sk", right_on="cd_demo_sk")
    j = j[(j.cd_gender == "F") & (j.cd_education_status == "College")
          & (j.d_year == 1998)]
    lvl = j.groupby("i_item_id", as_index=False).agg(
        agg1=("cs_quantity", "mean"), agg2=("cs_list_price", "mean"),
        agg3=("cs_coupon_amt", "mean"))
    total = pd.DataFrame({"i_item_id": [None],
                          "agg1": [j.cs_quantity.mean()],
                          "agg2": [j.cs_list_price.mean()],
                          "agg3": [j.cs_coupon_amt.mean()]})
    ref = pd.concat([lvl, total], ignore_index=True)
    ref = ref.sort_values("i_item_id", key=lambda c: pd.Categorical(
        c.fillna("￿"))).head(100)
    assert got["i_item_id"].fillna("~").tolist() == \
        ref["i_item_id"].fillna("~").tolist()
    for c in ("agg1", "agg2", "agg3"):
        # avg over decimal columns rounds to the column scale (Trino
        # semantics); the pandas oracle is exact — compare at half-ulp
        np.testing.assert_allclose(got[c].astype(float),
                                   ref[c].astype(float), atol=0.0051)


def test_q73_ticket_count_buckets(eng, host):
    """Q73 family: per-ticket item counts with a HAVING band, joined back —
    aggregate-as-build-side under a second aggregate."""
    e, s = eng
    got = e.execute_sql(
        "select cnt, count(*) n from ("
        "  select ss_ticket_number, ss_customer_sk, count(*) cnt "
        "  from store_sales, date_dim, household_demographics "
        "  where ss_sold_date_sk = d_date_sk and ss_hdemo_sk = hd_demo_sk "
        "  and d_year = 2000 and hd_vehicle_count > 1 "
        "  group by ss_ticket_number, ss_customer_sk "
        "  having count(*) between 2 and 10) x "
        "group by cnt order by cnt", s).to_pandas()
    ss, dd, hd = (host["store_sales"], host["date_dim"],
                  host["household_demographics"])
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk") \
        .merge(hd, left_on="ss_hdemo_sk", right_on="hd_demo_sk")
    j = j[(j.d_year == 2000) & (j.hd_vehicle_count > 1)]
    g = j.groupby(["ss_ticket_number", "ss_customer_sk"]).size()
    g = g[(g >= 2) & (g <= 10)]
    ref = g.value_counts().sort_index().reset_index()
    ref.columns = ["cnt", "n"]
    assert got["cnt"].tolist() == ref["cnt"].tolist()
    assert got["n"].tolist() == ref["n"].tolist()


def test_q42_category_revenue_rollup_by_year(eng, host):
    """ROLLUP over (d_year, i_category): the two-level monthly category
    report shape."""
    e, s = eng
    got = e.execute_sql(
        "select d_year, i_category, sum(ss_ext_sales_price) rev "
        "from date_dim, store_sales, item "
        "where d_date_sk = ss_sold_date_sk and ss_item_sk = i_item_sk "
        "and d_moy = 12 group by rollup (d_year, i_category) "
        "order by d_year, i_category limit 300", s).to_pandas()
    ss, dd, it = host["store_sales"], host["date_dim"], host["item"]
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk") \
        .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j = j[j.d_moy == 12]
    lvl2 = j.groupby(["d_year", "i_category"], as_index=False) \
        .ss_ext_sales_price.sum()
    lvl1 = j.groupby(["d_year"], as_index=False).ss_ext_sales_price.sum()
    lvl1["i_category"] = None
    total = pd.DataFrame({"d_year": [np.nan], "i_category": [None],
                          "ss_ext_sales_price": [j.ss_ext_sales_price.sum()]})
    ref = pd.concat([lvl2, lvl1, total], ignore_index=True) \
        .rename(columns={"ss_ext_sales_price": "rev"})
    ref = ref.sort_values(
        ["d_year", "i_category"],
        key=lambda c: (c.fillna(np.inf) if c.name == "d_year"
                       else pd.Categorical(c.fillna("￿")))).head(300)
    assert got["d_year"].fillna(-1).astype(float).tolist() == \
        ref["d_year"].fillna(-1).astype(float).tolist()
    assert got["i_category"].fillna("~").tolist() == \
        ref["i_category"].fillna("~").tolist()
    np.testing.assert_allclose(got["rev"].astype(float),
                               ref["rev"].astype(float), rtol=1e-9)


@pytest.fixture(scope="module")
def host_margin(eng):
    e, _ = eng
    conn = e.catalogs["tpcds"]
    return {
        "store_sales": _table(conn, "store_sales", [
            "ss_sold_date_sk", "ss_item_sk", "ss_ext_sales_price",
            "ss_net_profit"]),
        "web_sales": _table(conn, "web_sales", [
            "ws_sold_date_sk", "ws_item_sk", "ws_net_profit",
            "ws_ext_sales_price"]),
        "item": _table(conn, "item", ["i_item_sk", "i_category", "i_class"]),
        "date_dim": _table(conn, "date_dim", ["d_date_sk", "d_year"]),
    }


def test_q36_gross_margin_rollup(eng, host_margin):
    """Q36 family: gross-margin ROLLUP over (category, class) with grouping()
    exposing the aggregation level."""
    e, s = eng
    got = e.execute_sql(
        "select sum(ss_net_profit) / sum(ss_ext_sales_price) gm, "
        "i_category, i_class, grouping(i_category, i_class) lvl "
        "from store_sales, date_dim, item "
        "where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk "
        "and d_year = 2001 "
        "group by rollup (i_category, i_class) "
        "order by lvl desc, i_category, i_class limit 50", s).to_pandas()
    ss, dd, it = (host_margin["store_sales"], host_margin["date_dim"],
                  host_margin["item"])
    j = ss.merge(dd, left_on="ss_sold_date_sk", right_on="d_date_sk") \
        .merge(it, left_on="ss_item_sk", right_on="i_item_sk")
    j = j[j.d_year == 2001]
    total_gm = j.ss_net_profit.sum() / j.ss_ext_sales_price.sum()
    assert int(got["lvl"].iloc[0]) == 3
    np.testing.assert_allclose(float(got["gm"].iloc[0]), total_gm, rtol=1e-9)
    by_cat = j.groupby("i_category").agg(p=("ss_net_profit", "sum"),
                                         s=("ss_ext_sales_price", "sum"))
    cat_rows = got[got["lvl"] == 1].set_index("i_category")
    assert len(cat_rows) == len(by_cat)
    for cat, row in by_cat.iterrows():
        np.testing.assert_allclose(float(cat_rows.loc[cat, "gm"]),
                                   row.p / row.s, rtol=1e-9)


def test_q86_web_rollup_counts(eng, host_margin):
    """Q86 family: web-channel profit ROLLUP over (category, class); level
    cardinalities and grand total must reconcile."""
    e, s = eng
    got = e.execute_sql(
        "select sum(ws_net_profit) profit, i_category, i_class, "
        "grouping(i_category, i_class) lvl "
        "from web_sales, date_dim, item "
        "where ws_sold_date_sk = d_date_sk and ws_item_sk = i_item_sk "
        "and d_year = 2000 group by rollup (i_category, i_class) "
        "order by lvl desc, i_category, i_class", s).to_pandas()
    ws, dd, it = (host_margin["web_sales"], host_margin["date_dim"],
                  host_margin["item"])
    j = ws.merge(dd, left_on="ws_sold_date_sk", right_on="d_date_sk") \
        .merge(it, left_on="ws_item_sk", right_on="i_item_sk")
    j = j[j.d_year == 2000]
    n_cat = j.i_category.nunique()
    n_pairs = j.groupby(["i_category", "i_class"]).ngroups
    assert len(got) == 1 + n_cat + n_pairs
    np.testing.assert_allclose(float(got["profit"].iloc[0]),
                               j.ws_net_profit.sum(), rtol=1e-9)
    mid = got[got["lvl"] == 1]
    np.testing.assert_allclose(mid["profit"].astype(float).sum(),
                               j.ws_net_profit.sum(), rtol=1e-9)
