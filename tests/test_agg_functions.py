"""Variance/stddev family, bool_and/bool_or, arbitrary, approx_distinct.

Reference: operator/aggregation (VarianceAggregation, BooleanAndAggregation,
ApproximateCountDistinctAggregation, ArbitraryAggregationFunction) — results
validated against numpy on the same generated data.
"""

import numpy as np
import pytest

from trino_tpu import Engine
from trino_tpu.connectors.tpch import TpchConnector


@pytest.fixture(scope="module")
def eng():
    e = Engine()
    e.register_catalog("tpch", TpchConnector(sf=0.01, split_rows=1 << 12))
    return e, e.create_session("tpch")


def _lineitem_np(e):
    conn = e.catalogs["tpch"]
    cols = {c: [] for c in ["l_quantity", "l_returnflag", "l_orderkey"]}
    for sp in conn.splits("lineitem"):
        page = conn.generate(sp, list(cols))
        valid = np.asarray(page.valid_mask())
        for c in cols:
            cols[c].append(np.asarray(page.column(c))[valid])
    return {c: np.concatenate(v) for c, v in cols.items()}


def test_variance_stddev_global(eng):
    e, s = eng
    r = e.execute_sql("""select var_pop(l_quantity), var_samp(l_quantity),
                                stddev_pop(l_quantity), stddev(l_quantity),
                                variance(l_quantity)
                         from lineitem""", s).rows()[0]
    q = _lineitem_np(e)["l_quantity"] / 100.0  # decimal(15,2) raw -> value
    assert np.isclose(r[0], np.var(q), rtol=1e-9)
    assert np.isclose(r[1], np.var(q, ddof=1), rtol=1e-9)
    assert np.isclose(r[2], np.std(q), rtol=1e-9)
    assert np.isclose(r[3], np.std(q, ddof=1), rtol=1e-9)
    assert np.isclose(r[4], np.var(q, ddof=1), rtol=1e-9)


def test_variance_grouped(eng):
    e, s = eng
    rows = e.execute_sql("""select l_returnflag, var_pop(l_quantity)
                            from lineitem group by l_returnflag
                            order by l_returnflag""", s).rows()
    d = _lineitem_np(e)
    conn = e.catalogs["tpch"]
    rf_dict = conn.dictionaries("lineitem")["l_returnflag"]
    q = d["l_quantity"] / 100.0
    for flag, got in rows:
        fid = rf_dict.lookup(flag)
        expect = np.var(q[d["l_returnflag"] == fid])
        assert np.isclose(got, expect, rtol=1e-9), flag


def test_bool_and_or(eng):
    e, s = eng
    r = e.execute_sql("""select bool_and(l_quantity > 0), bool_or(l_quantity > 4900),
                                every(l_quantity > 2500)
                         from lineitem""", s).rows()[0]
    q = _lineitem_np(e)["l_quantity"]
    assert r[0] == bool((q > 0).all())
    assert r[1] == bool((q > 490000).any())
    assert r[2] == bool((q > 250000).all())


def test_approx_distinct_and_arbitrary(eng):
    e, s = eng
    r = e.execute_sql("select approx_distinct(l_orderkey) from lineitem", s).rows()[0]
    d = _lineitem_np(e)
    assert r[0] == len(np.unique(d["l_orderkey"]))
    rows = e.execute_sql("""select l_returnflag, approx_distinct(l_orderkey)
                            from lineitem group by l_returnflag
                            order by l_returnflag""", s).rows()
    conn = e.catalogs["tpch"]
    rf_dict = conn.dictionaries("lineitem")["l_returnflag"]
    for flag, got in rows:
        fid = rf_dict.lookup(flag)
        assert got == len(np.unique(d["l_orderkey"][d["l_returnflag"] == fid]))
    arb = e.execute_sql("select arbitrary(l_orderkey), any_value(l_orderkey) "
                        "from lineitem where l_orderkey = 7", s).rows()[0]
    assert arb == (7, 7)


def test_var_samp_single_row_is_undefined(eng):
    e, s = eng
    r = e.execute_sql("""select var_samp(l_quantity) from lineitem
                         where l_orderkey = 1 and l_linenumber = 1""", s).rows()[0]
    # <2 samples -> SQL NULL (aggregate outputs carry real null masks now)
    assert r[0] is None


def test_count_if_and_geometric_mean():
    """Sugar aggregates rewrite to supported compositions (reference:
    CountIfAggregation, GeometricMeanAggregations)."""
    import math

    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table t (g bigint, x double, b boolean)", s)
    e.execute_sql("insert into t values (1, 2.0, true), (1, 8.0, false), "
                  "(2, 3.0, true), (2, 9.0, true), (2, 1.0, null)", s)
    r = e.execute_sql(
        "select g, count_if(b) ci, geometric_mean(x) gm from t "
        "group by g order by g", s).to_pandas()
    assert r["ci"].tolist() == [1, 2]  # NULL conditions count as false
    assert abs(r["gm"].iloc[0] - 4.0) < 1e-9
    assert abs(r["gm"].iloc[1] - 27.0 ** (1 / 3)) < 1e-9
    r = e.execute_sql(
        "select g from t group by g having count_if(b) >= 2", s).to_pandas()
    assert r["g"].tolist() == [2]
    # scalar math over aggregate results in the post-agg scope
    r = e.execute_sql(
        "select g, sqrt(var_pop(x)) sd from t group by g order by g",
        s).to_pandas()
    assert abs(r["sd"].iloc[0] - 3.0) < 1e-9


def test_all_null_and_empty_groups_are_null(eng):
    """SQL aggregates over all-NULL or empty inputs are NULL, not 0/sentinel
    (reference: the null flags of the aggregation states)."""
    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    e2 = Engine()
    e2.register_catalog("mem", MemoryConnector())
    s2 = e2.create_session("mem")
    e2.execute_sql("create table t (g bigint, x bigint, d decimal(10,2))", s2)
    e2.execute_sql("insert into t values (1, null, null), (2, 5, 1.50)", s2)
    r = e2.execute_sql(
        "select g, sum(x) s, min(x) mn, max(x) mx, avg(x) a, sum(d) sd "
        "from t group by g order by g", s2).to_pandas()
    assert r.iloc[0, 1:].isna().all()  # all-NULL group
    assert r.iloc[1, 1:].tolist() == [5, 5, 5, 5.0, 1.5]
    # empty global aggregation
    r = e2.execute_sql("select sum(x) s, min(x) mn, count(x) c from t "
                       "where g = 99", s2).to_pandas()
    assert r["s"].isna().all() and r["mn"].isna().all()
    assert r["c"].tolist() == [0]  # count stays 0, never NULL
    # count_if of zero rows is 0 (a count), not NULL
    r = e2.execute_sql("select count_if(x > 0) ci from t where g = 99",
                       s2).to_pandas()
    assert r["ci"].tolist() == [0]


def test_mixed_distinct_aggregates(engine, tpch_pandas):
    """count(distinct x) beside plain aggregates and multiple distinct args
    (reference: MultipleDistinctAggregationToMarkDistinct — re-planned as
    per-part aggregations joined on the group keys)."""
    li = tpch_pandas["lineitem"]
    got = engine.execute_sql(
        "select l_returnflag, count(*) n, count(distinct l_suppkey) ds, "
        "count(distinct l_shipmode) dm, sum(l_quantity) q from lineitem "
        "group by l_returnflag order by l_returnflag").to_pandas()
    ref = li.groupby("l_returnflag").agg(
        n=("l_orderkey", "size"), ds=("l_suppkey", "nunique"),
        dm=("l_shipmode", "nunique"), q=("l_quantity", "sum")).reset_index()
    assert got["l_returnflag"].tolist() == ref["l_returnflag"].tolist()
    for c in ("n", "ds", "dm", "q"):
        np.testing.assert_allclose(got[c].astype(float), ref[c].astype(float))
    g = engine.execute_sql(
        "select count(*) n, count(distinct l_orderkey) o from lineitem"
    ).rows()[0]
    assert int(g[0]) == len(li) and int(g[1]) == li.l_orderkey.nunique()


def test_mixed_distinct_null_group_keys(engine):
    """NULL group keys survive the part-join composition (IS NOT DISTINCT
    FROM via coalesce-to-sentinel join keys)."""
    from trino_tpu import Engine
    from trino_tpu.connectors.memory import MemoryConnector

    e = Engine()
    e.register_catalog("mem", MemoryConnector())
    s = e.create_session("mem")
    e.execute_sql("create table md (k bigint, v bigint, w bigint)", s)
    e.execute_sql("insert into md values (1, 5, 7), (1, 5, 8), "
                  "(null, 6, 9), (null, 7, 9)", s)
    r = e.execute_sql("select k, count(*) c, count(distinct v) dv, sum(w) sw "
                      "from md group by k order by k", s).rows()
    assert r == [(1, 2, 1, 15), (None, 2, 2, 18)]
